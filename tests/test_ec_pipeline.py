"""Staged EC pipeline: bit-identity, crash-safety, decoder tails.

The contract under test (parallel/streaming.py + encoder/decoder):
  * pipelined and serial paths produce byte-identical shards — both walk
    the single layout.iter_encode_batches plan;
  * an interrupted pipeline (any stage) leaves NO .ecNN / .dat under a
    final name and no .tmp litter (AtomicFileGroup);
  * decoder.write_dat_file reassembles every tail shape, including the
    exactly-k*large_block size the old `>=` row loop misread.

Blocks are scaled down (LB=640/SB=160 vs 1GB/1MB) so the full two-tier
row structure — multiple large rows, small rows, partial tail — fits in
kilobytes; layout.py keeps the same strict-> split at any scale.
"""

import glob
import os

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import make_coder
from seaweedfs_tpu.parallel import streaming
from seaweedfs_tpu.storage.erasure_coding import decoder as ecdec
from seaweedfs_tpu.storage.erasure_coding import encoder as ecenc
from seaweedfs_tpu.storage.erasure_coding import layout

LB, SB = 640, 160
K = layout.DATA_SHARDS_COUNT
TOTAL = layout.TOTAL_SHARDS_COUNT


def _make_dat(base: str, size: int, seed: int = 0) -> bytes:
    dat = np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(dat)
    return dat


def _shards(base: str) -> list[bytes]:
    return [open(base + layout.shard_ext(i), "rb").read()
            for i in range(TOTAL)]


def _leftovers(d) -> list[str]:
    return sorted(os.path.basename(p) for p in glob.glob(str(d) + "/*")
                  if not p.endswith((".dat", ".keep")))


# ---- bit-identity: serial vs pipelined, all coder/reader variants ----

@pytest.mark.parametrize("size", [
    1,                          # single byte
    SB * K - 7,                 # partial small row, non-multiple of k*SB
    2 * LB * K,                 # exactly k*large_block (the `>=` bug size)
    2 * LB * K + 3,
    2 * LB * K + 3 * SB * K + 77,
])
def test_pipelined_matches_serial(tmp_path, size):
    sbase, pbase = str(tmp_path / "s"), str(tmp_path / "p")
    for b in (sbase, pbase):
        _make_dat(b, size, seed=size)
    ecenc.write_ec_files(sbase, make_coder("cpu"), LB, SB, batch_size=SB)
    ecenc.write_ec_files(pbase, make_coder("cpu-mt"), LB, SB,
                         batch_size=SB, pipelined=True)
    assert _shards(sbase) == _shards(pbase)


def test_pipelined_multi_reader_matches(tmp_path):
    sbase, pbase = str(tmp_path / "s"), str(tmp_path / "p")
    size = 3 * LB * K + 2 * SB * K + 11
    for b in (sbase, pbase):
        _make_dat(b, size, seed=2)
    ecenc.write_ec_files(sbase, make_coder("cpu"), LB, SB, batch_size=SB)
    # readers=2 interleave by sequence number; the coder stage reorders
    ecenc.write_ec_files(pbase, make_coder("cpu"), LB, SB, batch_size=SB,
                         pipelined=True, readers=2)
    assert _shards(sbase) == _shards(pbase)


def test_pipelined_odd_batch_snaps_to_block(tmp_path):
    # batch_size not dividing the block must snap to one-batch-per-block,
    # never split a row unevenly (layout.iter_encode_batches contract)
    sbase, pbase = str(tmp_path / "s"), str(tmp_path / "p")
    size = LB * K + SB * K + 5
    for b in (sbase, pbase):
        _make_dat(b, size, seed=3)
    ecenc.write_ec_files(sbase, make_coder("cpu"), LB, SB, batch_size=LB)
    ecenc.write_ec_files(pbase, make_coder("cpu"), LB, SB, batch_size=77,
                         pipelined=True)
    assert _shards(sbase) == _shards(pbase)


# ---- crash-safety: no truncated shard ever visible ----

class _BoomCoder:
    """Wraps a real coder; fails on the Nth encode call."""

    def __init__(self, blow_at: int):
        self._inner = make_coder("cpu")
        self.scheme = self._inner.scheme
        self.calls = 0
        self.blow_at = blow_at

    def encode_into(self, data, out):
        self.calls += 1
        if self.calls >= self.blow_at:
            raise RuntimeError("disk on fire")
        return np.asarray(self._inner.encode_array(data))

    def encode_array(self, data):
        self.calls += 1
        if self.calls >= self.blow_at:
            raise RuntimeError("disk on fire")
        return self._inner.encode_array(data)


def test_pipelined_encode_crash_leaves_nothing(tmp_path):
    base = str(tmp_path / "v")
    _make_dat(base, 2 * LB * K + SB * K)
    with pytest.raises(RuntimeError, match="disk on fire"):
        ecenc.write_ec_files(base, _BoomCoder(blow_at=3), LB, SB,
                             batch_size=SB, pipelined=True)
    assert _leftovers(tmp_path) == []


def test_serial_encode_crash_leaves_nothing(tmp_path):
    base = str(tmp_path / "v")
    _make_dat(base, 2 * LB * K + SB * K)
    with pytest.raises(RuntimeError, match="disk on fire"):
        ecenc.write_ec_files(base, _BoomCoder(blow_at=3), LB, SB,
                             batch_size=SB)
    assert _leftovers(tmp_path) == []


def test_pipelined_reader_stage_crash_raises_pipeline_error(
        tmp_path, monkeypatch):
    base = str(tmp_path / "v")
    _make_dat(base, 2 * LB * K + 2 * SB * K)
    real = streaming._read_rows
    state = {"n": 0}

    def flaky(f, buf, desc, k):
        state["n"] += 1
        if state["n"] == 4:
            raise IOError("surprise EIO")
        real(f, buf, desc, k)

    monkeypatch.setattr(streaming, "_read_rows", flaky)
    with pytest.raises(streaming.PipelineError) as ei:
        ecenc.write_ec_files(base, make_coder("cpu"), LB, SB,
                             batch_size=SB, pipelined=True)
    assert isinstance(ei.value.__cause__, IOError)
    assert _leftovers(tmp_path) == []


def test_rebuild_crash_on_truncated_survivor(tmp_path):
    base = str(tmp_path / "v")
    _make_dat(base, LB * K + 3 * SB * K)
    ecenc.write_ec_files(base, make_coder("cpu"), LB, SB, batch_size=SB)
    os.remove(base + layout.shard_ext(12))
    # survivor .ec05 loses its tail -> reader short-read -> abort
    # (not .ec00: the first source shard DEFINES shard_size, so its
    # truncation just shortens the walk instead of erroring)
    sz = os.path.getsize(base + layout.shard_ext(5))
    with open(base + layout.shard_ext(5), "r+b") as f:
        f.truncate(sz - 16)
    with pytest.raises(streaming.PipelineError):
        ecenc.rebuild_ec_files(base, make_coder("cpu"), batch_size=SB,
                               pipelined=True)
    assert not os.path.exists(base + layout.shard_ext(12))
    assert not glob.glob(str(tmp_path) + "/*.tmp")


# ---- pipelined rebuild / decode identity ----

@pytest.mark.parametrize("drop", [[1, 11], [0, 2, 11, 13]])
def test_pipelined_rebuild_matches_originals(tmp_path, drop):
    base = str(tmp_path / "v")
    _make_dat(base, 2 * LB * K + SB * K + 9, seed=5)
    ecenc.write_ec_files(base, make_coder("cpu"), LB, SB, batch_size=SB)
    want = _shards(base)
    for i in drop:
        os.remove(base + layout.shard_ext(i))
    got_ids = ecenc.rebuild_ec_files(base, make_coder("cpu-mt"),
                                     batch_size=SB, pipelined=True)
    assert sorted(got_ids) == sorted(drop)
    assert _shards(base) == want


@pytest.mark.parametrize("size", [
    1,
    SB * K - 7,
    SB * K * 5 + SB // 2,
    2 * LB * K,                 # regression: old `>=` read this as a
    2 * LB * K + 3,             # large row and scrambled the reassembly
    2 * LB * K + 3 * SB * K + 77,
])
@pytest.mark.parametrize("pipelined", [False, True])
def test_write_dat_file_roundtrip(tmp_path, size, pipelined):
    base = str(tmp_path / "v")
    dat = _make_dat(base, size, seed=size % 97)
    ecenc.write_ec_files(base, make_coder("cpu"), LB, SB, batch_size=SB)
    os.remove(base + ".dat")
    ecdec.write_dat_file(base, size, LB, SB, pipelined=pipelined)
    assert open(base + ".dat", "rb").read() == dat


@pytest.mark.parametrize("pipelined", [False, True])
def test_write_dat_file_crash_removes_tmp(tmp_path, pipelined):
    base = str(tmp_path / "v")
    size = LB * K + SB * K
    _make_dat(base, size)
    ecenc.write_ec_files(base, make_coder("cpu"), LB, SB, batch_size=SB)
    os.remove(base + ".dat")
    sz = os.path.getsize(base + layout.shard_ext(0))
    with open(base + layout.shard_ext(0), "r+b") as f:
        f.truncate(sz - 8)      # reader hits EOF before `take` satisfied
    with pytest.raises((IOError, streaming.PipelineError)):
        ecdec.write_dat_file(base, size, LB, SB, pipelined=pipelined)
    assert not os.path.exists(base + ".dat")
    assert not os.path.exists(base + ".dat.tmp")


# ---- multi-core CpuCoder sharding ----

def test_cpu_workers_bit_identical():
    from seaweedfs_tpu.ops import rs_cpu
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (K, 1 << 17), dtype=np.uint8)
    base = make_coder("cpu").encode_array(data)
    for native in (True, False):
        if native and rs_cpu._native() is None:
            continue
        mt = rs_cpu.CpuCoder(use_native=native, workers=3)
        assert np.array_equal(mt.encode_array(data), base), native


def test_cpu_mt_registered_and_auto_workers():
    from seaweedfs_tpu.ops import rs_cpu
    mt = make_coder("cpu-mt")
    assert mt.workers == rs_cpu.auto_workers() >= 1
    rng = np.random.default_rng(10)
    data = rng.integers(0, 256, (K, 4096), dtype=np.uint8)
    assert np.array_equal(mt.encode_array(data),
                          make_coder("cpu").encode_array(data))


def test_numpy_fallback_methods_agree():
    """pair16 (production fallback) vs split-nibble (independent method)
    vs the native kernel: three GF(256) matrix-apply implementations,
    one answer."""
    from seaweedfs_tpu.ops import rs_cpu
    from seaweedfs_tpu.ops.gf256 import rs_matrix
    rng = np.random.default_rng(11)
    mat = np.asarray(rs_matrix(10, 14))[10:]
    for n in (1, 2, 63, 64, 65, 4097):
        data = rng.integers(0, 256, (10, n), dtype=np.uint8)
        out = np.zeros((4, n), dtype=np.uint8)
        rs_cpu._gf_apply_numpy_into(mat, data, out)
        assert np.array_equal(out, rs_cpu._gf_apply_nibble(mat, data)), n
        if rs_cpu._native() is not None:
            assert np.array_equal(
                out, rs_cpu._gf_apply(mat, data, use_native=True)), n
