"""Assign-lease lane: master-outage-tolerant fid minting.

The master grants volume servers epoch-stamped fid-range leases on the
heartbeat reply; holders mint fids locally via /admin/lease_assign and
clients (wdclient) prefer that lane over /dir/assign. These tests pin
the grant/install/mint/refuse ladder against real in-process servers,
the wdclient leader re-resolution on 503, and the assign_leases=False
comparator (bit-identical stored bytes either way).
"""

import time

import pytest

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import (HttpError, HttpServer, Response,
                                       http_json)
from seaweedfs_tpu.utils.resilience import Deadline, deadline_scope


@pytest.fixture
def duo(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    deadline = time.time() + 5
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    yield master, vs
    vs.stop()
    master.stop()


def _wait_lease(vs, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with vs._lease_lock:
            if vs._leases:
                return dict(next(iter(vs._leases.values())))
        time.sleep(0.1)
    raise AssertionError("holder never received a lease")


def test_heartbeat_grants_lease_and_holder_mints_locally(duo):
    master, vs = duo
    mc = MasterClient(master.url)
    # first assign grows the volume (master path); the next heartbeat
    # asks for a lease on it and the grant rides the reply back
    first = mc.assign()
    assert first.get("fid"), first
    lease = _wait_lease(vs)
    assert lease["epoch"] >= 1
    assert lease["key_hi"] > lease["key_lo"]
    assert master.lease_counters["grant"] >= 1

    # now the lane mints without the master: upload + readback through
    # a lease-minted fid is bit-identical
    out = mc.assign()
    assert out.get("lease_epoch") == lease["epoch"], out
    assert mc.lease_assigns == 1
    data = b"leased needle payload" * 64
    operation.upload_to(out["fid"], out["url"], data)
    assert operation.read_data(mc, out["fid"]) == data
    assert vs.lease_stats["minted"] >= 1

    # the lease table is visible to operators and clients
    reply = http_json("GET", f"http://{master.url}/cluster/leases")
    assert reply["is_leader"]
    assert reply["counters"]["grant"] >= 1
    vids = [l["vid"] for l in reply["leases"]]
    assert lease["vid"] in vids


def test_leased_writes_survive_master_outage(duo):
    """The tentpole proof at unit scale: with a warm lease, the master
    process can die and every write still completes."""
    master, vs = duo
    mc = MasterClient(master.url)
    assert mc.assign().get("fid")
    _wait_lease(vs)
    mc.assign()  # warm the client's lease directory too
    master.stop()
    try:
        blobs = {}
        t0 = time.time()
        for i in range(10):
            out = mc.assign()
            assert out.get("fid") and "error" not in out, out
            data = f"dark-window write {i}".encode() * 32
            operation.upload_to(out["fid"], out["url"], data)
            blobs[out["fid"]] = data
        assert time.time() - t0 < 5.0, "writes stalled on the dead master"
        assert mc.lease_assigns >= 11
        # readback straight from the holder (lookup would need a master)
        from seaweedfs_tpu.utils.httpd import http_call
        for fid, data in blobs.items():
            status, body, _ = http_call("GET",
                                        f"http://{vs.url}/{fid}",
                                        timeout=5)
            assert status == 200 and body == data
    finally:
        vs.stop()


def test_unleased_holder_refuses_503_and_client_falls_back(duo):
    master, vs = duo
    mc = MasterClient(master.url)
    # no volume yet -> no lease -> the holder must refuse, not mint
    with pytest.raises(HttpError) as ei:
        http_json("POST", f"http://{vs.url}/admin/lease_assign",
                  timeout=3)
    assert ei.value.status == 503
    assert vs.lease_stats["refused"] >= 1
    # the client's assign still succeeds via the master fallback
    out = mc.assign()
    assert out.get("fid"), out
    assert mc.lease_fallbacks >= 1


def test_draining_holder_refuses_lease_mints(duo):
    master, vs = duo
    mc = MasterClient(master.url)
    assert mc.assign().get("fid")
    _wait_lease(vs)
    vs.draining = True
    try:
        with pytest.raises(HttpError) as ei:
            http_json("POST", f"http://{vs.url}/admin/lease_assign",
                      timeout=3)
        assert ei.value.status == 503
    finally:
        vs.draining = False


def test_shell_cluster_leases_command(duo):
    """weed-tpu shell `cluster.leases`: the master's grant table plus
    each holder's own mint/refuse stats, through the same dispatch the
    operator types at."""
    from seaweedfs_tpu.shell.commands import ShellContext
    from seaweedfs_tpu.shell.repl import run_command

    master, vs = duo
    mc = MasterClient(master.url)
    assert mc.assign().get("fid")
    _wait_lease(vs)
    mc.assign()  # one holder-minted fid so the stats are non-zero
    out = run_command(ShellContext(master.url, use_grpc=False),
                      "cluster.leases")
    assert out["is_leader"] is True
    assert out["counters"]["grant"] >= 1
    leases = out["leases"]
    assert leases and all(l["key_hi"] >= l["key_lo"] for l in leases)
    assert all(l["remaining_s"] > 0 for l in leases)
    holder = leases[0]["holder"]
    assert out["holders"][holder]["installed"] >= 1
    assert out["holders"][holder]["minted"] >= 1


def test_call_503_reresolves_leader_from_peer_status():
    """wdclient._call on a 503 without a usable hint probes the peer
    list's /cluster/status and retries at whoever it names leader."""
    confused = HttpServer()
    confused.add("POST", "/dir/assign",
                 lambda req: Response({"error": "shedding"}, status=503))
    confused.add("GET", "/cluster/status",
                 lambda req: Response({"IsLeader": False,
                                       "Leader": leader_url[0]}))
    confused.start()
    leader = HttpServer()
    leader.add("POST", "/dir/assign",
               lambda req: Response({"fid": "1,00000001deadbeef",
                                     "url": "x", "count": 1}))
    leader.add("GET", "/cluster/status",
               lambda req: Response({"IsLeader": True,
                                     "Leader": leader_url[0]}))
    leader.start()
    leader_url = [f"127.0.0.1:{leader.port}"]
    try:
        mc = MasterClient([f"127.0.0.1:{confused.port}"],
                          assign_leases=False)
        out = mc.assign()
        assert out.get("fid") == "1,00000001deadbeef"
        assert mc.leader == leader_url[0]
    finally:
        confused.stop()
        leader.stop()


def test_ambient_deadline_bounds_the_master_dance():
    """An expiring ambient deadline cuts the leader-hunt short instead
    of grinding through every round x candidate x backoff."""
    mc = MasterClient(["127.0.0.1:1", "127.0.0.1:2"],
                      assign_leases=False)
    t0 = time.time()
    with deadline_scope(Deadline.after(0.5)):
        with pytest.raises((ConnectionError, HttpError)):
            mc._call("POST", "/dir/assign?count=1")
    assert time.time() - t0 < 3.0


def test_comparator_lane_off_same_bytes(duo):
    """assign_leases=False is the pre-lease protocol; stored bytes are
    bit-identical through either lane."""
    master, vs = duo
    leased = MasterClient(master.url)
    legacy = MasterClient(master.url, assign_leases=False)
    assert legacy.assign().get("fid")
    _wait_lease(vs)

    data = b"\x00comparator payload\xff" * 128
    a = leased.assign()
    assert a.get("lease_epoch"), a  # minted by the holder
    b = legacy.assign()
    assert "lease_epoch" not in b   # minted by the master
    assert legacy.lease_assigns == 0
    operation.upload_to(a["fid"], a["url"], data)
    operation.upload_to(b["fid"], b["url"], data)
    assert operation.read_data(leased, a["fid"]) \
        == operation.read_data(legacy, b["fid"]) == data
    # and the two lanes never minted overlapping keys: the leased range
    # was carved from the same replicated sequence the master mints from
    assert a["fid"] != b["fid"]
