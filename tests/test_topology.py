"""Topology/layout/growth tests — in-memory cluster-state fixtures, the
reference's own strategy for testing multi-node logic without nodes
(weed/shell/command_ec_test.go, command_volume_balance_test.go)."""

import pytest

from seaweedfs_tpu.cluster.sequence import MemorySequencer, SnowflakeSequencer
from seaweedfs_tpu.cluster.topology import Topology
from seaweedfs_tpu.cluster.volume_growth import (NoFreeSpaceError,
                                                 find_empty_slots,
                                                 grow_by_type)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement


def _hb(ip, port, volumes=(), ec=(), dc="dc1", rack="r1", maxv=10):
    return {
        "ip": ip, "port": port, "data_center": dc, "rack": rack,
        "max_volume_count": maxv,
        "volumes": list(volumes), "ec_shards": list(ec),
    }


def _vol(vid, size=0, collection="", rp=0, read_only=False):
    return {"id": vid, "size": size, "collection": collection,
            "replica_placement": rp, "read_only": read_only,
            "file_count": 1, "delete_count": 0, "deleted_byte_count": 0,
            "ttl": 0, "version": 3}


def test_register_and_lookup():
    topo = Topology(volume_size_limit=1000)
    n1 = topo.sync_data_node_registration(_hb("a", 1, [_vol(1), _vol(2)]))
    n2 = topo.sync_data_node_registration(_hb("b", 2, [_vol(1)], rack="r2"))
    assert {n.id for n in topo.lookup("", 1)} == {"a:1", "b:2"}
    assert [n.id for n in topo.lookup("", 2)] == ["a:1"]
    lo = topo.get_layout("", "000", "")
    assert 1 in lo.writable and 2 in lo.writable
    vid, locs = lo.pick_for_write()
    assert vid in (1, 2)

    # full resync without volume 2 -> unregistered
    topo.sync_data_node_registration(_hb("a", 1, [_vol(1)]))
    assert topo.lookup("", 2) == []
    assert 2 not in lo.writable

    # node death removes its volumes
    topo.unregister_data_node(n2)
    assert [n.id for n in topo.lookup("", 1)] == ["a:1"]


def test_oversized_and_readonly_not_writable():
    topo = Topology(volume_size_limit=100)
    topo.sync_data_node_registration(
        _hb("a", 1, [_vol(1, size=200), _vol(2, read_only=True), _vol(3)]))
    lo = topo.get_layout("", "000", "")
    assert lo.writable == {3}


def test_replica_layout_needs_enough_copies():
    topo = Topology(volume_size_limit=1000)
    rp = ReplicaPlacement.parse("001").to_byte()
    topo.sync_data_node_registration(_hb("a", 1, [_vol(1, rp=rp)]))
    lo = topo.get_layout("", "001", "")
    assert 1 not in lo.writable  # only 1 of 2 copies present
    topo.sync_data_node_registration(_hb("b", 2, [_vol(1, rp=rp)]))
    assert 1 in lo.writable


def test_ec_shard_map():
    topo = Topology()
    topo.sync_data_node_registration(
        _hb("a", 1, ec=[{"id": 5, "ec_index_bits": 0b11111}]))
    topo.sync_data_node_registration(
        _hb("b", 2, ec=[{"id": 5, "ec_index_bits": 0b11111111100000}]))
    shards = topo.lookup_ec_shards(5)
    assert [n.id for n in shards[0]] == ["a:1"]
    assert [n.id for n in shards[13]] == ["b:2"]
    # delta: node b drops shard 13
    nb = topo.find_node("b:2")
    topo.incremental_sync(nb, {"deleted_ec_shards":
                               [{"id": 5, "ec_index_bits": 1 << 13}]})
    assert topo.lookup_ec_shards(5)[13] == []
    assert nb.ec_shards[5] == 0b1111111100000


def test_find_empty_slots_placement():
    topo = Topology()
    for dc in ("dc1", "dc2"):
        for rack in ("r1", "r2"):
            for i in range(2):
                topo.sync_data_node_registration(
                    _hb(f"{dc}-{rack}-{i}", 80, dc=dc, rack=rack))
    # 010: one replica on a different rack, same dc
    nodes = find_empty_slots(topo, ReplicaPlacement.parse("010"))
    assert len(nodes) == 2
    assert nodes[0].rack.id != nodes[1].rack.id
    assert nodes[0].rack.data_center.id == nodes[1].rack.data_center.id
    # 100: one replica in a different dc
    nodes = find_empty_slots(topo, ReplicaPlacement.parse("100"))
    assert len(nodes) == 2
    assert nodes[0].rack.data_center.id != nodes[1].rack.data_center.id
    # 001: same rack, different node
    nodes = find_empty_slots(topo, ReplicaPlacement.parse("001"))
    assert len(nodes) == 2
    assert nodes[0].rack is nodes[1].rack and nodes[0] is not nodes[1]
    # 200 impossible with 2 DCs
    with pytest.raises(NoFreeSpaceError):
        find_empty_slots(topo, ReplicaPlacement.parse("200"))


def test_grow_by_type_allocates_and_assigns_ids():
    topo = Topology()
    for i in range(3):
        topo.sync_data_node_registration(_hb(f"n{i}", 80))
    allocated = []

    def alloc(node, vid, collection, rp, ttl, disk=""):
        allocated.append((node.id, vid))
        node.volumes[vid] = _vol(vid)
        topo._register_volume(_vol(vid), node)
        return True

    vids = grow_by_type(topo, "", "001", "", alloc, count=2)
    assert len(vids) == 2 and vids[0] != vids[1]
    assert len(allocated) == 4  # 2 volumes x 2 copies
    assert topo.max_volume_id == max(vids)


def test_sequencers():
    s = MemorySequencer()
    a = s.next_file_id(3)
    b = s.next_file_id()
    assert b == a + 3
    s.set_max(100)
    assert s.next_file_id() == 101

    sf = SnowflakeSequencer(node_id=5)
    ids = {sf.next_file_id() for _ in range(100)}
    assert len(ids) == 100


def test_prune_dead_nodes():
    topo = Topology(pulse_seconds=0.01)
    n = topo.sync_data_node_registration(_hb("a", 1, [_vol(1)]))
    n.last_seen -= 10
    dead = topo.prune_dead_nodes()
    assert [d.id for d in dead] == ["a:1"]
    assert topo.lookup("", 1) == []
