"""Filer depth: LSM embedded store, manifest chunks, hard links, and
per-path filer.conf rules (reference weed/filer/leveldb*,
filechunk_manifest.go, filerstore_hardlink.go, filer_conf.go)."""

import time

import pytest

from seaweedfs_tpu.filer import filer_conf as fc
from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filechunk_manifest import (maybe_manifestize,
                                                    resolve_chunk_manifest)
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import MemoryStore, SqliteStore
from seaweedfs_tpu.filer.lsm_store import LsmStore
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call, http_json


# ---- store contract, now including the LSM store ----

def _contract(s):
    s.insert_entry(Entry("/a/b/file.txt", Attr(mtime=1.0, file_size=5)))
    assert s.find_entry("/a/b/file.txt").attr.file_size == 5
    s.insert_entry(Entry("/a/b/other.txt"))
    s.insert_entry(Entry("/a/b/sub", Attr(is_directory=True)))
    s.insert_entry(Entry("/a/b/sub/deep.txt"))
    assert [x.name for x in s.list_directory_entries("/a/b")] == [
        "file.txt", "other.txt", "sub"]
    assert [x.name for x in s.list_directory_entries(
        "/a/b", prefix="o")] == ["other.txt"]
    assert [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt")] == ["other.txt", "sub"]
    assert [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt", include_start=True)] == [
        "file.txt", "other.txt", "sub"]
    s.delete_entry("/a/b/other.txt")
    assert s.find_entry("/a/b/other.txt") is None
    s.delete_folder_children("/a/b")
    assert s.list_directory_entries("/a/b") == []
    assert s.find_entry("/a/b/sub/deep.txt") is None
    s.kv_put(b"conf", b"xyz")
    assert s.kv_get(b"conf") == b"xyz"
    assert s.kv_get(b"missing") is None
    s.kv_delete(b"conf")
    assert s.kv_get(b"conf") is None


@pytest.mark.parametrize("kind", ["memory", "sqlite", "lsm"])
def test_store_contract_all_stores(kind, tmp_path):
    if kind == "lsm":
        s = LsmStore(str(tmp_path / "lsm"))
    else:
        s = {"memory": MemoryStore, "sqlite": SqliteStore}[kind]()
    _contract(s)
    s.close()


def test_lsm_durability_and_compaction(tmp_path):
    path = str(tmp_path / "lsm")
    s = LsmStore(path, flush_keys=8, compact_at=3)
    for i in range(100):
        s.insert_entry(Entry(f"/d/f{i:03d}", Attr(file_size=i)))
    for i in range(0, 100, 3):
        s.delete_entry(f"/d/f{i:03d}")
    # reopen WITHOUT close: WAL replay must recover the memtable tail
    s2 = LsmStore(path)
    assert s2.find_entry("/d/f001").attr.file_size == 1
    assert s2.find_entry("/d/f000") is None  # tombstone survived
    names = [e.name for e in s2.list_directory_entries("/d", limit=1000)]
    assert len(names) == 100 - len(range(0, 100, 3))
    s2.close()
    # clean close flushes; a third open reads pure SSTables
    s3 = LsmStore(path)
    assert s3.find_entry("/d/f098").attr.file_size == 98
    s3.close()


# ---- manifest chunks ----

def test_manifest_roundtrip():
    blobs = {}

    def save(blob):
        fid = f"m,{len(blobs)}"
        blobs[fid] = blob
        return FileChunk(fid=fid, offset=0, size=len(blob))

    leaves = [FileChunk(f"1,{i}", i * 10, 10, mtime_ns=i)
              for i in range(257)]
    packed = maybe_manifestize(save, list(leaves), batch=16)
    assert len(packed) <= 16
    assert any(c.is_chunk_manifest for c in packed)
    resolved = resolve_chunk_manifest(lambda c: blobs[c.fid], packed)
    assert sorted(c.fid for c in resolved) == sorted(c.fid for c in leaves)
    assert {(c.offset, c.size) for c in resolved} == {
        (c.offset, c.size) for c in leaves}


def test_manifestize_noop_when_narrow():
    packed = maybe_manifestize(lambda b: FileChunk("x", 0, len(b)),
                               [FileChunk("1,a", 0, 5)])
    assert [c.fid for c in packed] == ["1,a"]


# ---- hard links ----

def test_hard_links_share_data_until_last_unlink():
    deleted = []
    f = Filer(delete_chunks_fn=lambda fids: deleted.extend(fids))
    e = Entry("/docs/a.txt", Attr(mtime=1.0))
    e.chunks = [FileChunk("3,abc", 0, 100, mtime_ns=1)]
    f.create_entry(e)

    link = f.add_hard_link("/docs/a.txt", "/docs/b.txt")
    assert link.hard_link_id
    got = f.find_entry("/docs/b.txt")
    assert [c.fid for c in got.chunks] == ["3,abc"]
    # the original resolves through the shared record too
    src = f.find_entry("/docs/a.txt")
    assert src.hard_link_id == link.hard_link_id
    assert [c.fid for c in src.chunks] == ["3,abc"]

    # update through one name is visible through the other
    src.chunks = [FileChunk("3,def", 0, 50, mtime_ns=2)]
    f.update_entry(src)
    assert [c.fid for c in f.find_entry("/docs/b.txt").chunks] == ["3,def"]

    # a rename must not change the link count
    f.rename_entry("/docs/b.txt", "/docs/c.txt")
    assert f.find_entry("/docs/c.txt") is not None

    f.delete_entry("/docs/a.txt")
    assert deleted == []  # still one name left
    assert [c.fid for c in f.find_entry("/docs/c.txt").chunks] == ["3,def"]
    f.delete_entry("/docs/c.txt")
    assert deleted == ["3,def"]  # last name gone -> chunks GC'd


def test_hard_links_in_listing():
    f = Filer()
    e = Entry("/x/a", Attr(mtime=1.0))
    e.chunks = [FileChunk("7,z", 0, 42, mtime_ns=1)]
    f.create_entry(e)
    f.add_hard_link("/x/a", "/x/b")
    listed = {x.name: x for x in f.list_entries("/x")}
    assert listed["b"].file_size() == 42


def test_hardlink_overwrite_one_name_keeps_shared_data():
    deleted = []
    f = Filer(delete_chunks_fn=lambda fids: deleted.extend(fids))
    e = Entry("/w/a", Attr(mtime=1.0))
    e.chunks = [FileChunk("9,shared", 0, 10, mtime_ns=1)]
    f.create_entry(e)
    f.add_hard_link("/w/a", "/w/b")
    # overwrite /w/a with new content: shared chunks must survive via /w/b
    fresh = Entry("/w/a", Attr(mtime=2.0))
    fresh.chunks = [FileChunk("9,new", 0, 5, mtime_ns=2)]
    f.create_entry(fresh)
    assert deleted == []
    assert [c.fid for c in f.find_entry("/w/b").chunks] == ["9,shared"]
    f.delete_entry("/w/b")
    assert deleted == ["9,shared"]


def test_manifest_chunks_gc_expands_leaves():
    blobs = {}

    def save(blob):
        fid = f"m,{len(blobs)}"
        blobs[fid] = blob
        return FileChunk(fid=fid, offset=0, size=len(blob))

    deleted = []
    f = Filer(delete_chunks_fn=lambda fids: deleted.extend(fids),
              read_chunk_fn=lambda c: blobs[c.fid])
    leaves = [FileChunk(f"5,{i}", i * 10, 10, mtime_ns=1) for i in range(20)]
    packed = maybe_manifestize(save, leaves, batch=4)
    e = Entry("/g/wide", Attr(mtime=1.0))
    e.chunks = packed
    f.create_entry(e)
    f.delete_entry("/g/wide")
    # every leaf AND every manifest blob is freed
    assert {f"5,{i}" for i in range(20)} <= set(deleted)
    assert set(blobs) <= set(deleted)


def test_extended_attrs_survive_hardlink_and_roundtrip():
    f = Filer()
    e = Entry("/t/tagged", Attr(mtime=1.0))
    e.extended = {"x-amz-tag": "v1", "raw": b"\x01\x02"}
    e.chunks = [FileChunk("4,t", 0, 3, mtime_ns=1)]
    f.create_entry(e)
    f.add_hard_link("/t/tagged", "/t/alias")
    got = f.find_entry("/t/alias")
    assert got.extended["x-amz-tag"] == "v1"
    assert got.extended["raw"] == b"\x01\x02"  # bytes survive the codec


# ---- filer.conf ----

def test_filer_conf_longest_prefix_merge():
    conf = fc.FilerConf()
    conf.set_rule(fc.PathConf("/buckets/", collection="", replication="001"))
    conf.set_rule(fc.PathConf("/buckets/hot/", collection="hot",
                              ttl="1h"))
    conf.set_rule(fc.PathConf("/frozen/", read_only=True))
    r = conf.match_storage_rule("/buckets/hot/obj")
    assert r.collection == "hot" and r.replication == "001"
    assert r.ttl == "1h"
    assert conf.match_storage_rule("/frozen/f").read_only
    assert not conf.match_storage_rule("/other").read_only
    # persistence round-trip through a store's KV space
    store = MemoryStore()
    conf.save(store)
    loaded = fc.FilerConf.load(store)
    assert len(loaded.rules) == 3
    loaded.delete_rule("/frozen/")
    assert len(loaded.rules) == 2


# ---- end-to-end over a live stack ----

@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    time.sleep(0.2)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def test_filer_conf_http_and_read_only(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    http_json("POST", f"{base}/__api/filer_conf",
              {"location_prefix": "/frozen/", "read_only": True})
    status, _, _ = http_call("POST", f"{base}/frozen/x", body=b"no")
    assert status == 403
    # read_only also gates delete / rename / hardlink
    status, _, _ = http_call("DELETE", f"{base}/frozen/x")
    assert status == 403
    status, body, _ = http_call(
        "POST", f"{base}/__api/rename",
        body=b'{"from": "/frozen/x", "to": "/elsewhere/x"}')
    assert status == 403
    http_json("POST", f"{base}/__api/filer_conf",
              {"location_prefix": "/frozen/", "delete": True})
    status, _, _ = http_call("POST", f"{base}/frozen/x", body=b"yes")
    assert status == 201
    conf = http_json("GET", f"{base}/__api/filer_conf")
    assert conf["locations"] == []


def test_filer_manifest_end_to_end(stack, monkeypatch):
    _, _, fs = stack
    import seaweedfs_tpu.server.filer_server as mod
    monkeypatch.setattr(mod, "CHUNK_SIZE", 1024)
    monkeypatch.setattr(mod, "INLINE_LIMIT", 16)

    # force manifestization with a tiny batch
    orig = mod.maybe_manifestize
    monkeypatch.setattr(mod, "maybe_manifestize",
                        lambda save, chunks, batch=4: orig(save, chunks, 4))
    base = f"http://{fs.url}"
    data = bytes(range(256)) * 64  # 16KB -> 16 chunks -> manifests
    status, _, _ = http_call("POST", f"{base}/m/wide.bin", body=data)
    assert status == 201
    entry = fs.filer.find_entry("/m/wide.bin")
    assert any(c.is_chunk_manifest for c in entry.chunks)
    assert len(entry.chunks) <= 4
    status, body, _ = http_call("GET", f"{base}/m/wide.bin")
    assert status == 200 and body == data


def test_filer_hardlink_http(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    http_call("POST", f"{base}/h/orig.txt", body=b"shared bytes")
    out = http_json("POST", f"{base}/__api/hardlink",
                    {"from": "/h/orig.txt", "to": "/h/link.txt"})
    assert out["hard_link_id"]
    status, body, _ = http_call("GET", f"{base}/h/link.txt")
    assert status == 200 and body == b"shared bytes"
    http_call("DELETE", f"{base}/h/orig.txt")
    status, body, _ = http_call("GET", f"{base}/h/link.txt")
    assert status == 200 and body == b"shared bytes"


def test_lsm_kv_empty_value_is_found(tmp_path):
    s = LsmStore(str(tmp_path / "kvlsm"))
    s.kv_put(b"empty", b"")
    assert s.kv_get(b"empty") == b""
    assert s.kv_get(b"missing") is None
    s.kv_delete(b"empty")
    assert s.kv_get(b"empty") is None
    s.close()


def test_lsm_torn_wal_tail_dropped(tmp_path):
    """A crash mid-append leaves a torn final WAL record; replay must
    drop it rather than resurrect a truncated key/value."""
    import os as _os
    from seaweedfs_tpu.utils.lsm import LsmKv
    d = str(tmp_path / "torn")
    kv = LsmKv(d)
    kv.put(b"alpha", b"1" * 100)
    kv.put(b"beta", b"2" * 100)
    # no close(): a crash leaves the records only in the WAL
    path = _os.path.join(d, "wal.log")
    size = _os.path.getsize(path)
    assert size > 30
    with open(path, "r+b") as f:
        f.truncate(size - 30)  # tear the last record's value
    kv = LsmKv(d)
    assert kv.get(b"alpha") == b"1" * 100
    got = kv.get(b"beta")
    assert got is None or got == b"2" * 100  # never a shortened blob
    # replay must have truncated the torn tail so appends go after the
    # last good record — otherwise the torn record resurrects on the
    # next replay, half-merged with the new one
    kv.put(b"gamma", b"3" * 50)
    # second crash (no close -> no memtable flush) and second replay
    kv2 = LsmKv(d)
    assert kv2.get(b"alpha") == b"1" * 100
    assert kv2.get(b"gamma") == b"3" * 50
    assert kv2.get(b"beta") is None  # dropped, not resurrected corrupt
    kv2.close()
