"""S3 gateway tests over a live mini-stack (reference model:
test/s3/basic/basic_test.go drives the real S3 API against weed server)."""

import time
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from seaweedfs_tpu.gateway.s3_server import S3Server
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call


@pytest.fixture
def s3stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs)
    s3.start()
    time.sleep(0.2)
    yield s3
    s3.stop()
    fs.stop()
    vs.stop()
    master.stop()


def test_bucket_lifecycle(s3stack):
    base = f"http://{s3stack.url}"
    status, _, _ = http_call("PUT", f"{base}/mybucket")
    assert status == 200
    status, body, _ = http_call("GET", f"{base}/")
    assert status == 200 and b"<Name>mybucket</Name>" in body
    status, _, _ = http_call("HEAD", f"{base}/mybucket")
    assert status == 200
    status, _, _ = http_call("DELETE", f"{base}/mybucket")
    assert status == 204
    status, _, _ = http_call("HEAD", f"{base}/mybucket")
    assert status == 404


def test_object_put_get_delete(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/b1")
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 5_000_000, dtype=np.uint8).tobytes()
    status, _, headers = http_call("PUT", f"{base}/b1/dir/obj.bin",
                                   body=data)
    assert status == 200 and headers.get("ETag")
    status, body, _ = http_call("GET", f"{base}/b1/dir/obj.bin")
    assert status == 200 and body == data

    # range read
    status, body, headers = http_call(
        "GET", f"{base}/b1/dir/obj.bin",
        headers={"Range": "bytes=100-199"})
    assert status == 206 and body == data[100:200]

    status, _, _ = http_call("DELETE", f"{base}/b1/dir/obj.bin")
    assert status == 204
    status, _, _ = http_call("GET", f"{base}/b1/dir/obj.bin")
    assert status == 404

    # missing bucket
    status, _, _ = http_call("PUT", f"{base}/nobucket/x", body=b"d")
    assert status == 404


def test_list_objects_v2(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/lst")
    for key in ("a/1.txt", "a/2.txt", "b/3.txt", "top.txt"):
        http_call("PUT", f"{base}/lst/{key}", body=b"x" * 10)
    status, body, _ = http_call("GET", f"{base}/lst?list-type=2")
    assert status == 200
    root = ET.fromstring(body)
    keys = sorted(c.find("Key").text for c in root.findall("Contents"))
    assert keys == ["a/1.txt", "a/2.txt", "b/3.txt", "top.txt"]

    # prefix filter
    status, body, _ = http_call("GET", f"{base}/lst?list-type=2&prefix=a/")
    keys = sorted(c.find("Key").text
                  for c in ET.fromstring(body).findall("Contents"))
    assert keys == ["a/1.txt", "a/2.txt"]

    # delimiter rolls up common prefixes
    status, body, _ = http_call(
        "GET", f"{base}/lst?list-type=2&delimiter=/")
    root = ET.fromstring(body)
    cps = sorted(p.find("Prefix").text
                 for p in root.findall("CommonPrefixes"))
    assert cps == ["a/", "b/"]
    keys = [c.find("Key").text for c in root.findall("Contents")]
    assert keys == ["top.txt"]


def test_multipart_upload(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/mp")
    status, body, _ = http_call("POST", f"{base}/mp/big.bin?uploads")
    upload_id = ET.fromstring(body).find("UploadId").text

    rng = np.random.default_rng(1)
    parts = [rng.integers(0, 256, 3_000_000, dtype=np.uint8).tobytes()
             for _ in range(3)]
    for i, p in enumerate(parts, start=1):
        status, _, _ = http_call(
            "PUT", f"{base}/mp/big.bin?uploadId={upload_id}&partNumber={i}",
            body=p)
        assert status == 200
    status, body, _ = http_call(
        "POST", f"{base}/mp/big.bin?uploadId={upload_id}", body=b"<x/>")
    assert status == 200 and b"CompleteMultipartUploadResult" in body

    status, body, _ = http_call("GET", f"{base}/mp/big.bin")
    assert status == 200 and body == b"".join(parts)


def test_delete_objects_batch(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/db")
    http_call("PUT", f"{base}/db/x.txt", body=b"1")
    http_call("PUT", f"{base}/db/y.txt", body=b"2")
    payload = (b"<Delete><Object><Key>x.txt</Key></Object>"
               b"<Object><Key>y.txt</Key></Object></Delete>")
    status, body, _ = http_call("POST", f"{base}/db?delete", body=payload)
    assert status == 200
    assert body.count(b"<Deleted>") == 2
    status, _, _ = http_call("GET", f"{base}/db/x.txt")
    assert status == 404


def test_sigv4_auth_rejects_anonymous(tmp_path):
    master = MasterServer()
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs, access_key="AKID", secret_key="SECRET")
    s3.start()
    try:
        status, body, _ = http_call("GET", f"http://{s3.url}/")
        assert status == 403 and b"AccessDenied" in body
    finally:
        s3.stop()
        fs.stop()
        vs.stop()
        master.stop()
