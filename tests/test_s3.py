"""S3 gateway tests over a live mini-stack (reference model:
test/s3/basic/basic_test.go drives the real S3 API against weed server)."""

import time
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from seaweedfs_tpu.gateway.s3_server import S3Server
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call


@pytest.fixture
def s3stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs)
    s3.start()
    time.sleep(0.2)
    yield s3
    s3.stop()
    fs.stop()
    vs.stop()
    master.stop()


def test_bucket_lifecycle(s3stack):
    base = f"http://{s3stack.url}"
    status, _, _ = http_call("PUT", f"{base}/mybucket")
    assert status == 200
    status, body, _ = http_call("GET", f"{base}/")
    assert status == 200 and b"<Name>mybucket</Name>" in body
    status, _, _ = http_call("HEAD", f"{base}/mybucket")
    assert status == 200
    status, _, _ = http_call("DELETE", f"{base}/mybucket")
    assert status == 204
    status, _, _ = http_call("HEAD", f"{base}/mybucket")
    assert status == 404


def test_object_put_get_delete(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/b1")
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 5_000_000, dtype=np.uint8).tobytes()
    status, _, headers = http_call("PUT", f"{base}/b1/dir/obj.bin",
                                   body=data)
    assert status == 200 and headers.get("ETag")
    status, body, _ = http_call("GET", f"{base}/b1/dir/obj.bin")
    assert status == 200 and body == data

    # range read
    status, body, headers = http_call(
        "GET", f"{base}/b1/dir/obj.bin",
        headers={"Range": "bytes=100-199"})
    assert status == 206 and body == data[100:200]

    status, _, _ = http_call("DELETE", f"{base}/b1/dir/obj.bin")
    assert status == 204
    status, _, _ = http_call("GET", f"{base}/b1/dir/obj.bin")
    assert status == 404

    # missing bucket
    status, _, _ = http_call("PUT", f"{base}/nobucket/x", body=b"d")
    assert status == 404


def test_list_objects_v2(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/lst")
    for key in ("a/1.txt", "a/2.txt", "b/3.txt", "top.txt"):
        http_call("PUT", f"{base}/lst/{key}", body=b"x" * 10)
    status, body, _ = http_call("GET", f"{base}/lst?list-type=2")
    assert status == 200
    root = ET.fromstring(body)
    keys = sorted(c.find("Key").text for c in root.findall("Contents"))
    assert keys == ["a/1.txt", "a/2.txt", "b/3.txt", "top.txt"]

    # prefix filter
    status, body, _ = http_call("GET", f"{base}/lst?list-type=2&prefix=a/")
    keys = sorted(c.find("Key").text
                  for c in ET.fromstring(body).findall("Contents"))
    assert keys == ["a/1.txt", "a/2.txt"]

    # delimiter rolls up common prefixes
    status, body, _ = http_call(
        "GET", f"{base}/lst?list-type=2&delimiter=/")
    root = ET.fromstring(body)
    cps = sorted(p.find("Prefix").text
                 for p in root.findall("CommonPrefixes"))
    assert cps == ["a/", "b/"]
    keys = [c.find("Key").text for c in root.findall("Contents")]
    assert keys == ["top.txt"]


def test_multipart_upload(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/mp")
    status, body, _ = http_call("POST", f"{base}/mp/big.bin?uploads")
    upload_id = ET.fromstring(body).find("UploadId").text

    rng = np.random.default_rng(1)
    parts = [rng.integers(0, 256, 3_000_000, dtype=np.uint8).tobytes()
             for _ in range(3)]
    for i, p in enumerate(parts, start=1):
        status, _, _ = http_call(
            "PUT", f"{base}/mp/big.bin?uploadId={upload_id}&partNumber={i}",
            body=p)
        assert status == 200
    status, body, _ = http_call(
        "POST", f"{base}/mp/big.bin?uploadId={upload_id}", body=b"<x/>")
    assert status == 200 and b"CompleteMultipartUploadResult" in body

    status, body, _ = http_call("GET", f"{base}/mp/big.bin")
    assert status == 200 and body == b"".join(parts)


def test_delete_objects_batch(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/db")
    http_call("PUT", f"{base}/db/x.txt", body=b"1")
    http_call("PUT", f"{base}/db/y.txt", body=b"2")
    payload = (b"<Delete><Object><Key>x.txt</Key></Object>"
               b"<Object><Key>y.txt</Key></Object></Delete>")
    status, body, _ = http_call("POST", f"{base}/db?delete", body=payload)
    assert status == 200
    assert body.count(b"<Deleted>") == 2
    status, _, _ = http_call("GET", f"{base}/db/x.txt")
    assert status == 404


def test_sigv4_auth_rejects_anonymous(tmp_path):
    master = MasterServer()
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs, access_key="AKID", secret_key="SECRET")
    s3.start()
    try:
        status, body, _ = http_call("GET", f"http://{s3.url}/")
        assert status == 403 and b"AccessDenied" in body
    finally:
        s3.stop()
        fs.stop()
        vs.stop()
        master.stop()


def test_list_objects_v1_marker(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/v1l")
    for key in ("a.txt", "b.txt", "c.txt"):
        http_call("PUT", f"{base}/v1l/{key}", body=b"x")
    status, body, _ = http_call("GET", f"{base}/v1l?max-keys=2")
    assert status == 200
    root = ET.fromstring(body)
    keys = [c.find("Key").text for c in root.findall("Contents")]
    assert keys == ["a.txt", "b.txt"]
    assert root.find("IsTruncated").text == "true"
    marker = root.find("NextMarker").text
    status, body, _ = http_call("GET", f"{base}/v1l?marker={marker}")
    root = ET.fromstring(body)
    keys = [c.find("Key").text for c in root.findall("Contents")]
    assert keys == ["c.txt"]


def test_copy_object(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/src")
    http_call("PUT", f"{base}/dst")
    payload = bytes(np.random.default_rng(7).integers(0, 256, 9000,
                                                      dtype=np.uint8))
    http_call("PUT", f"{base}/src/orig.bin", body=payload,
              headers={"x-amz-tagging": "team=infra"})
    status, body, _ = http_call(
        "PUT", f"{base}/dst/copy.bin", body=b"",
        headers={"x-amz-copy-source": "/src/orig.bin"})
    assert status == 200 and b"CopyObjectResult" in body
    status, body, _ = http_call("GET", f"{base}/dst/copy.bin")
    assert status == 200 and body == payload
    # tags are copied by default (COPY directive)
    _, body, _ = http_call("GET", f"{base}/dst/copy.bin?tagging")
    assert b"team" in body and b"infra" in body
    # deleting the source must not break the copy
    http_call("DELETE", f"{base}/src/orig.bin")
    status, body, _ = http_call("GET", f"{base}/dst/copy.bin")
    assert status == 200 and body == payload
    # missing source
    status, _, _ = http_call(
        "PUT", f"{base}/dst/x.bin", body=b"",
        headers={"x-amz-copy-source": "/src/nope.bin"})
    assert status == 404


def test_object_tagging(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/tg")
    http_call("PUT", f"{base}/tg/o.txt", body=b"hi",
              headers={"x-amz-tagging": "a=1&b=two"})
    status, body, _ = http_call("GET", f"{base}/tg/o.txt?tagging")
    assert status == 200
    root = ET.fromstring(body)
    tags = {t.find("Key").text: t.find("Value").text
            for t in root.iter("Tag")}
    assert tags == {"a": "1", "b": "two"}
    # replace via PUT ?tagging
    put_body = (b'<Tagging><TagSet><Tag><Key>c</Key><Value>3</Value>'
                b'</Tag></TagSet></Tagging>')
    status, _, _ = http_call("PUT", f"{base}/tg/o.txt?tagging",
                             body=put_body)
    assert status == 200
    _, body, _ = http_call("GET", f"{base}/tg/o.txt?tagging")
    root = ET.fromstring(body)
    tags = {t.find("Key").text: t.find("Value").text
            for t in root.iter("Tag")}
    assert tags == {"c": "3"}
    # delete all tags
    status, _, _ = http_call("DELETE", f"{base}/tg/o.txt?tagging")
    assert status == 204
    _, body, _ = http_call("GET", f"{base}/tg/o.txt?tagging")
    assert b"<Tag>" not in body
    # object data unaffected
    _, body, _ = http_call("GET", f"{base}/tg/o.txt")
    assert body == b"hi"


def test_bucket_stubs(s3stack):
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/stub")
    status, body, _ = http_call("GET", f"{base}/stub?location")
    assert status == 200 and b"LocationConstraint" in body
    status, body, _ = http_call("GET", f"{base}/stub?versioning")
    assert status == 200 and b"VersioningConfiguration" in body
    status, body, _ = http_call("GET", f"{base}/stub?acl")
    assert status == 200 and b"FULL_CONTROL" in body
    status, body, _ = http_call("GET", f"{base}/stub?uploads")
    assert status == 200 and b"ListMultipartUploadsResult" in body


def test_circuit_breaker(s3stack):
    from seaweedfs_tpu.gateway.s3_server import CircuitBreaker
    cb = CircuitBreaker(global_read=2, buckets={"hot": {"Write": 1}})
    assert cb.acquire("b", "Read") and cb.acquire("c", "Read")
    assert not cb.acquire("d", "Read")          # global read limit hit
    cb.release("b", "Read")
    assert cb.acquire("d", "Read")
    assert cb.acquire("hot", "Write")
    assert not cb.acquire("hot", "Write")       # bucket write limit hit
    assert cb.acquire("cold", "Write")          # other buckets unaffected
    # wired into the server: saturate and expect 503
    base = f"http://{s3stack.url}"
    http_call("PUT", f"{base}/cbk")
    http_call("PUT", f"{base}/cbk/f.txt", body=b"d")
    s3stack.breaker.global_limits["Read"] = 1
    s3stack.breaker.acquire("cbk", "Read")
    try:
        status, body, _ = http_call("GET", f"{base}/cbk/f.txt")
        assert status == 503 and b"TooManyRequests" in body
    finally:
        s3stack.breaker.release("cbk", "Read")
        s3stack.breaker.global_limits["Read"] = 0
    status, _, _ = http_call("GET", f"{base}/cbk/f.txt")
    assert status == 200


def _sigv4_presign(method, host, path, akid, secret, expires=900):
    import hashlib
    import hmac
    import urllib.parse
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    query = {
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": f"{akid}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    cq = "&".join(f"{urllib.parse.quote(k, safe='~')}="
                  f"{urllib.parse.quote(v, safe='~')}"
                  for k, v in sorted(query.items()))
    # sign the percent-encoded wire path verbatim, like real clients
    creq = "\n".join([method, path, cq,
                      f"host:{host}\n", "host", "UNSIGNED-PAYLOAD"])
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    k = ("AWS4" + secret).encode()
    for msg in (date, "us-east-1", "s3", "aws4_request"):
        k = hmac.new(k, msg.encode(), hashlib.sha256).digest()
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    query["X-Amz-Signature"] = sig
    return (f"http://{host}{path}?" +
            urllib.parse.urlencode(query))


@pytest.fixture
def s3auth(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "va")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs, access_key="AKID", secret_key="SECRET")
    s3.start()
    time.sleep(0.2)
    yield s3
    s3.stop()
    fs.stop()
    vs.stop()
    master.stop()


def test_presigned_url(s3auth):
    host = s3auth.url
    # seed a bucket+object directly through the filer (bypassing auth)
    s3auth.filer.mkdirs("/buckets/pre")
    from seaweedfs_tpu.filer.entry import Attr, Entry
    e = Entry("/buckets/pre/doc.txt",
              attr=Attr(mtime=time.time(), crtime=time.time(),
                        file_size=5))
    e.content = b"hello"
    s3auth.filer.create_entry(e)
    # unsigned request is rejected
    status, _, _ = http_call("GET", f"http://{host}/pre/doc.txt")
    assert status == 403
    # presigned GET succeeds
    url = _sigv4_presign("GET", host, "/pre/doc.txt", "AKID", "SECRET")
    status, body, _ = http_call("GET", url)
    assert status == 200 and body == b"hello"
    # tampered signature fails
    bad = url[:-4] + "0000"
    status, _, _ = http_call("GET", bad)
    assert status == 403
    # presigned PUT works too
    url = _sigv4_presign("PUT", host, "/pre/up.txt", "AKID", "SECRET")
    status, _, _ = http_call("PUT", url, body=b"data!")
    assert status == 200
    url = _sigv4_presign("GET", host, "/pre/up.txt", "AKID", "SECRET")
    status, body, _ = http_call("GET", url)
    assert body == b"data!"
    # percent-encoded key: signature covers the wire path verbatim
    url = _sigv4_presign("PUT", host, "/pre/a%20b.txt", "AKID", "SECRET")
    status, _, _ = http_call("PUT", url, body=b"spaced")
    assert status == 200
    url = _sigv4_presign("GET", host, "/pre/a%20b.txt", "AKID", "SECRET")
    status, body, _ = http_call("GET", url)
    assert status == 200 and body == b"spaced"


def test_post_policy_upload(s3auth):
    import base64
    import hashlib
    import hmac
    import json
    host = s3auth.url
    s3auth.filer.mkdirs("/buckets/forms")
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    policy = base64.b64encode(json.dumps({
        "expiration": time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                    time.gmtime(time.time() + 600)),
        "conditions": [{"bucket": "forms"}],
    }).encode()).decode()
    k = b"AWS4SECRET"
    for msg in (date, "us-east-1", "s3", "aws4_request"):
        k = hmac.new(k, msg.encode(), hashlib.sha256).digest()
    sig = hmac.new(k, policy.encode(), hashlib.sha256).hexdigest()
    boundary = "testboundary123"
    fields = {
        "key": "uploads/${filename}",
        "policy": policy,
        "x-amz-credential": f"AKID/{scope}",
        "x-amz-signature": sig,
        "success_action_status": "201",
    }
    parts = []
    for name, val in fields.items():
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f"name=\"{name}\"\r\n\r\n{val}\r\n".encode())
    parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                 f"name=\"file\"; filename=\"report.pdf\"\r\n"
                 f"Content-Type: application/pdf\r\n\r\n".encode()
                 + b"PDFDATA" + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    body = b"".join(parts)
    status, _, _ = http_call(
        "POST", f"http://{host}/forms", body=body,
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    assert status == 201
    url = _sigv4_presign("GET", host, "/forms/uploads/report.pdf",
                         "AKID", "SECRET")
    status, body, _ = http_call("GET", url)
    assert status == 200 and body == b"PDFDATA"
    # bad signature rejected
    fields["x-amz-signature"] = "0" * 64
    parts = []
    for name, val in fields.items():
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f"name=\"{name}\"\r\n\r\n{val}\r\n".encode())
    parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                 f"name=\"file\"; filename=\"x\"\r\n\r\n".encode()
                 + b"NO" + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    status, _, _ = http_call(
        "POST", f"http://{host}/forms", body=b"".join(parts),
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    assert status == 403


def test_post_policy_conditions(s3auth):
    import base64
    import hashlib
    import hmac
    import json
    host = s3auth.url
    s3auth.filer.mkdirs("/buckets/open")
    s3auth.filer.mkdirs("/buckets/locked")
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"

    def signed_policy(conditions):
        policy = base64.b64encode(json.dumps({
            "expiration": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(time.time() + 600)),
            "conditions": conditions,
        }).encode()).decode()
        k = b"AWS4SECRET"
        for msg in (date, "us-east-1", "s3", "aws4_request"):
            k = hmac.new(k, msg.encode(), hashlib.sha256).digest()
        return policy, hmac.new(k, policy.encode(),
                                hashlib.sha256).hexdigest()

    def post(bucket, key, data, conditions):
        policy, sig = signed_policy(conditions)
        boundary = "bnd42"
        fields = {"key": key, "policy": policy,
                  "x-amz-credential": f"AKID/{scope}",
                  "x-amz-signature": sig}
        parts = [f"--{boundary}\r\nContent-Disposition: form-data; "
                 f"name=\"{n}\"\r\n\r\n{v}\r\n".encode()
                 for n, v in fields.items()]
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f"name=\"file\"; filename=\"f\"\r\n\r\n".encode()
                     + data + b"\r\n")
        parts.append(f"--{boundary}--\r\n".encode())
        status, _, _ = http_call(
            "POST", f"http://{host}/{bucket}", body=b"".join(parts),
            headers={"Content-Type":
                     f"multipart/form-data; boundary={boundary}"})
        return status

    conds = [{"bucket": "open"}, ["starts-with", "$key", "in/"],
             ["content-length-range", 1, 100]]
    # policy scoped to bucket "open" must not write elsewhere
    assert post("locked", "in/a.txt", b"hi", conds) == 403
    # key outside starts-with prefix rejected
    assert post("open", "out/a.txt", b"hi", conds) == 403
    # oversize body rejected
    assert post("open", "in/big.txt", b"x" * 200, conds) == 403
    # conforming upload succeeds; ISO expiration without millis accepted
    assert post("open", "in/a.txt", b"hi\n", conds) == 204
    url = _sigv4_presign("GET", host, "/open/in/a.txt", "AKID", "SECRET")
    status, body, _ = http_call("GET", url)
    # trailing newline in the payload survives multipart parsing
    assert status == 200 and body == b"hi\n"
