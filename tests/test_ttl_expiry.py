"""TTL volume expiry (reference volume_checking.go expired/
expiredLongEnough + topology_event_handling: TTL volumes die whole
once their newest write ages past the TTL; reads 404 immediately at
expiry, files are reaped after a removal grace)."""

import os
import time

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import NotFoundError, Store


def _hours_ago(h: float) -> int:
    return int((time.time() - h * 3600) * 1e9)


def test_ttl_volume_expires_whole(tmp_path):
    store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
    v = store.add_volume(1, ttl="1m")
    store.write_volume_needle(1, Needle(id=7, cookie=0xAB, data=b"brief"))
    assert not v.is_expired()
    assert store.read_volume_needle(1, 7, 0xAB).data == b"brief"

    # age the newest write 2 hours past a 1-minute TTL
    v.last_append_at_ns = _hours_ago(2)
    assert v.is_expired() and v.is_expired_long_enough()
    # reads 404 even before the files are reaped
    try:
        store.read_volume_needle(1, 7, 0xAB)
        raise AssertionError("expired volume still served a read")
    except NotFoundError:
        pass

    store.drain_deltas()  # clear the add delta
    assert store.delete_expired_ttl_volumes() == [1]
    assert store.find_volume(1) is None
    assert not os.path.exists(tmp_path / "1.dat")
    deltas = store.drain_deltas()
    assert [d["id"] for d in deltas["deleted_volumes"]] == [1]


def test_expiry_grace_and_activity_reset(tmp_path):
    store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
    v = store.add_volume(2, ttl="1h")
    store.write_volume_needle(2, Needle(id=1, cookie=1, data=b"x"))
    # expired but within the removal grace (ttl/10 = 6min for a 1h TTL,
    # reference volume.go expiredLongEnough): reads gone, files kept
    v.last_append_at_ns = _hours_ago(1.2)
    assert v.is_expired_long_enough()  # past the 6min grace
    v.last_append_at_ns = _hours_ago(1.05)
    assert v.is_expired() and not v.is_expired_long_enough()
    assert store.delete_expired_ttl_volumes() == []
    assert store.find_volume(2) is not None
    # a fresh write resets the clock (lastModified semantics)
    store.write_volume_needle(2, Needle(id=2, cookie=1, data=b"y"))
    assert not v.is_expired()
    assert store.read_volume_needle(2, 2, 1).data == b"y"


def test_reaper_skips_compacting_and_rechecks(tmp_path):
    """A vacuum in progress or a write acked after the scan must stop
    the reaper (review findings: destroy-mid-vacuum / acked-write
    loss)."""
    store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
    v = store.add_volume(4, ttl="1m")
    store.write_volume_needle(4, Needle(id=1, cookie=1, data=b"a"))
    v.last_append_at_ns = _hours_ago(2)
    v.is_compacting = True
    assert store.delete_expired_ttl_volumes() == []
    assert store.find_volume(4) is not None
    v.is_compacting = False
    assert store.delete_expired_ttl_volumes() == [4]


def test_replica_copy_preserves_ttl_clock(tmp_path):
    """volume.copy carries the source .dat mtime so the new replica
    expires on the ORIGINAL schedule, not a fresh one."""
    import time as _time

    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.shell.commands import ShellContext
    from seaweedfs_tpu.utils.httpd import http_call

    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs1 = VolumeServer([str(tmp_path / "a")], master.url)
    vs2 = VolumeServer([str(tmp_path / "b")], master.url)
    vs1.start()
    vs2.start()
    _time.sleep(0.3)
    try:
        import urllib.request
        a = __import__("json").loads(urllib.request.urlopen(
            f"http://{master.url}/dir/assign?ttl=1h").read())
        fid = a["fid"]
        vid = int(fid.split(",")[0])
        status, _, _ = http_call("POST", f"http://{a['url']}/{fid}",
                                 body=b"ttl payload")
        assert status < 300
        # age the source files two hours into the past
        src_vs = vs1 if a["url"] == vs1.url else vs2
        dst_vs = vs2 if src_vs is vs1 else vs1
        v = src_vs.store.find_volume(vid)
        v.sync()
        old = _time.time() - 7200
        os.utime(v.file_name() + ".dat", (old, old))
        os.utime(v.file_name() + ".idx", (old, old))
        ShellContext(master.url).volume_copy(vid, src_vs.url, dst_vs.url)
        copied = dst_vs.store.find_volume(vid)
        assert copied is not None
        mtime = os.stat(copied.file_name() + ".dat").st_mtime
        assert abs(mtime - old) < 5, "copy restarted the TTL clock"
        # and the copy is therefore already expired, like the source
        assert copied.is_expired()
    finally:
        vs2.stop()
        vs1.stop()
        master.stop()


def test_non_ttl_volume_never_expires(tmp_path):
    store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
    v = store.add_volume(3)
    store.write_volume_needle(3, Needle(id=1, cookie=1, data=b"z"))
    v.last_append_at_ns = _hours_ago(1000)
    assert not v.is_expired()
    assert store.delete_expired_ttl_volumes() == []
    assert store.read_volume_needle(3, 1, 1).data == b"z"
