"""Regression tests for the concrete defects weedlint surfaced when it
first ran over the tree (see ARCHITECTURE.md "Static analysis &
invariants"): the master's KeepConnected broadcast wedging on one slow
subscriber, the gRPC sync pump buffering an unbounded event backlog,
and long-lived protocol sockets silently inheriting their connect
timeout as the per-op I/O timeout.  The lint gate itself
(test_weedlint.py) keeps the *patterns* from coming back; these pin
the repaired *behavior*."""

import queue
import socket
import threading
import time

import pytest


# ---- master KeepConnected: bounded per-subscriber queues --------------

class _StubTopo:
    def __init__(self):
        self.listeners = []


class _StubMaster:
    def __init__(self):
        self.topo = _StubTopo()


def test_master_broadcast_sheds_oldest_for_slow_subscriber():
    """_broadcast must never block while holding the subscriber lock:
    a full (stalled) subscriber queue loses its OLDEST delta to make
    room for the newest, and healthy subscribers still get every
    delta."""
    from seaweedfs_tpu.server.master_grpc import (MasterGrpc,
                                                  SUB_QUEUE_DEPTH)

    mg = MasterGrpc(_StubMaster())
    slow: queue.Queue = queue.Queue(maxsize=SUB_QUEUE_DEPTH)
    healthy: queue.Queue = queue.Queue(maxsize=SUB_QUEUE_DEPTH)
    for i in range(SUB_QUEUE_DEPTH):
        slow.put_nowait(f"old-{i}")
    with mg._subs_lock:
        mg._subs[1] = slow
        mg._subs[2] = healthy

    done = threading.Event()

    def bcast():
        mg._broadcast("new-delta")
        done.set()

    threading.Thread(target=bcast, daemon=True).start()
    assert done.wait(2.0), "_broadcast blocked on a full subscriber"
    assert healthy.get_nowait() == "new-delta"
    drained = []
    while True:
        try:
            drained.append(slow.get_nowait())
        except queue.Empty:
            break
    assert drained[0] == "old-1", "oldest delta should have been shed"
    assert drained[-1] == "new-delta"
    assert len(drained) == SUB_QUEUE_DEPTH


# ---- gRPC sync pump: bounded queue backpressures the stream -----------

class _StubCall:
    """Iterable standing in for a grpc SubscribeMetadata stream that
    never ends; counts how far the pump has read it."""

    def __init__(self):
        self.pulled = 0
        self.cancelled = False

    def __iter__(self):
        while not self.cancelled:
            self.pulled += 1
            yield ("ev", self.pulled)

    def cancel(self):
        self.cancelled = True


class _StubClient:
    def __init__(self, call):
        self._call = call

    def subscribe_metadata(self, since_ns, path_prefix):
        return self._call


def test_sync_pump_backpressures_instead_of_buffering(monkeypatch):
    """With the consumer stalled, the pump thread must stop reading the
    stream once the queue fills — bounded memory — instead of slurping
    the whole backlog."""
    from seaweedfs_tpu.replication import sync as sync_mod

    monkeypatch.setattr(sync_mod, "_pb_event_to_dict", lambda resp: resp)
    call = _StubCall()
    gen = sync_mod._grpc_event_stream(_StubClient(call), 0, "/")
    assert next(gen) is not None  # starts the pump
    deadline = time.monotonic() + 2.0
    while call.pulled < 100 and time.monotonic() < deadline:
        time.sleep(0.01)  # let the pump fill the queue
    high = call.pulled
    time.sleep(0.3)       # consumer stalled: pump must be parked
    # one extra item can be in flight inside the blocked put()
    assert call.pulled <= high + 1 <= 260, \
        f"pump read {call.pulled} events with a stalled consumer"
    gen.close()           # cancels the stream via the finally branch
    assert call.cancelled


# ---- long-lived sockets: explicit I/O timeout after connect -----------

@pytest.fixture
def listener():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    yield srv.getsockname()
    srv.close()


def test_store_clients_set_explicit_io_timeout(listener):
    """The filer-store wire clients must not let the connect timeout
    silently persist as the per-op I/O timeout — the socket deadline
    after __init__ is the explicit one the client chose."""
    from seaweedfs_tpu.filer.redis_store import RespClient

    host, port = listener
    c = RespClient(host, port, timeout=3.5)
    try:
        assert c.sock.gettimeout() == 3.5
    finally:
        c.sock.close()


def test_kafka_producer_sets_explicit_io_timeout(listener):
    from seaweedfs_tpu.notification.kafka_queue import KafkaProducer

    host, port = listener
    p = KafkaProducer(host, port)
    try:
        assert p.sock.gettimeout() == p.timeout
    finally:
        p.sock.close()
