"""FUSE mount tests: WeedFS logic directly, and — when the environment
allows mount(2) — a REAL kernel mount exercised with plain os calls."""

import errno
import os
import stat
import subprocess
import time

import pytest

from seaweedfs_tpu.mount.fuse_kernel import ROOT_ID, FuseError
from seaweedfs_tpu.mount.weedfs import WeedFS
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture
def stack(tmp_path):
    master = MasterServer()
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    time.sleep(0.1)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def test_weedfs_operations(stack):
    """Drive the Operations interface directly (no kernel involved)."""
    master, vs, fs = stack
    w = WeedFS(fs)

    # create + write + flush + read back through a fresh handle
    attr, fh = w.create(ROOT_ID, "hello.txt", 0o644)
    assert w.write(attr.ino, fh, 0, b"hello ") == 6
    assert w.write(attr.ino, fh, 6, b"world") == 5
    w.release(attr.ino, fh)

    got = w.lookup(ROOT_ID, "hello.txt")
    assert got is not None and got.size == 11
    fh2 = w.open(got.ino)
    assert w.read(got.ino, fh2, 0, 100) == b"hello world"
    w.release(got.ino, fh2)

    # mkdir + rename into it
    dattr = w.mkdir(ROOT_ID, "sub", 0o755)
    assert stat.S_ISDIR(dattr.mode)
    assert w.rename(ROOT_ID, "hello.txt", dattr.ino, "moved.txt") == 0
    assert w.lookup(ROOT_ID, "hello.txt") is None
    assert w.lookup(dattr.ino, "moved.txt").size == 11

    # readdir
    names = [n for n, _ in w.readdir(ROOT_ID)]
    assert "sub" in names and "." in names

    # truncate via setattr
    m = w.lookup(dattr.ino, "moved.txt")
    fh3 = w.open(m.ino)
    a = w.setattr(m.ino, 1 << 3, size=5, mode=0, mtime=0, fh=fh3)
    w.release(m.ino, fh3)
    assert w.lookup(dattr.ino, "moved.txt").size == 5

    # symlink + readlink + hard link (direct ops)
    s = w.symlink(ROOT_ID, "lnk", "sub/moved.txt")
    assert s is not None and stat.S_ISLNK(s.mode)
    assert w.readlink(s.ino) == "sub/moved.txt"
    assert w.symlink(ROOT_ID, "lnk", "x") is None  # EEXIST
    m2 = w.lookup(dattr.ino, "moved.txt")
    h = w.link(m2.ino, ROOT_ID, "hard.txt")
    assert h is not None
    # POSIX: linking onto an existing name is EEXIST, not a replace
    import pytest as _pytest
    with _pytest.raises(FileExistsError):
        w.link(m2.ino, ROOT_ID, "hard.txt")
    fh4 = w.open(h.ino)
    assert w.read(h.ino, fh4, 0, 100) == b"hello"
    w.release(h.ino, fh4)
    assert w.unlink(ROOT_ID, "hard.txt") == 0
    assert w.unlink(ROOT_ID, "lnk") == 0
    # statfs returns cluster-shaped numbers
    st = w.statfs()
    assert st is not None and st[0] > 0

    # unlink + rmdir
    assert w.unlink(dattr.ino, "moved.txt") == 0
    assert w.rmdir(ROOT_ID, "sub") == 0
    assert w.unlink(ROOT_ID, "nope") == errno.ENOENT


def test_real_kernel_mount(stack, tmp_path):
    """Mount through /dev/fuse and use normal filesystem calls."""
    master, vs, fs = stack
    from seaweedfs_tpu.mount.fuse_kernel import FuseConnection
    mnt = tmp_path / "mnt"
    mnt.mkdir()
    w = WeedFS(fs)
    try:
        conn = FuseConnection(w, str(mnt))
    except (FuseError, PermissionError, OSError) as e:
        pytest.skip(f"cannot mount fuse here: {e}")
    conn.serve_forever(background=True)
    try:
        p = mnt / "kernel.txt"
        p.write_bytes(b"written through the kernel")
        assert p.read_bytes() == b"written through the kernel"
        assert p.stat().st_size == 26

        (mnt / "d").mkdir()
        (mnt / "d" / "nested.bin").write_bytes(b"x" * 5000)
        assert sorted(os.listdir(mnt)) == ["d", "kernel.txt"]
        assert (mnt / "d" / "nested.bin").read_bytes() == b"x" * 5000

        os.rename(mnt / "kernel.txt", mnt / "d" / "renamed.txt")
        assert not p.exists()
        assert (mnt / "d" / "renamed.txt").read_bytes() == \
            b"written through the kernel"

        # the file is genuinely in the filer (visible via HTTP)
        from seaweedfs_tpu.utils.httpd import http_call
        status, body, _ = http_call("GET", f"http://{fs.url}/d/renamed.txt")
        assert status == 200 and body == b"written through the kernel"

        # symlinks through the kernel (reference weedfs_symlink.go)
        os.symlink("d/renamed.txt", mnt / "alias")
        assert os.readlink(mnt / "alias") == "d/renamed.txt"
        assert (mnt / "alias").read_bytes() == \
            b"written through the kernel"
        assert os.lstat(mnt / "alias").st_mode & 0o170000 == stat.S_IFLNK

        # hard links share data (reference weedfs_link.go)
        os.link(mnt / "d" / "nested.bin", mnt / "hard.bin")
        assert (mnt / "hard.bin").read_bytes() == b"x" * 5000
        os.remove(mnt / "d" / "nested.bin")
        # data survives while the second name exists
        assert (mnt / "hard.bin").read_bytes() == b"x" * 5000

        # statfs reflects cluster capacity
        sv = os.statvfs(mnt)
        assert sv.f_blocks > 0 and sv.f_bfree > 0

        # extended attributes through the kernel (weedfs_xattr.go)
        target = str(mnt / "d" / "renamed.txt")
        os.setxattr(target, "user.color", b"blue")
        os.setxattr(target, "user.shape", b"\x00binary\xff")
        assert os.getxattr(target, "user.color") == b"blue"
        assert os.getxattr(target, "user.shape") == b"\x00binary\xff"
        assert sorted(os.listxattr(target)) == ["user.color",
                                                "user.shape"]
        with pytest.raises(OSError):  # XATTR_CREATE on existing
            os.setxattr(target, "user.color", b"red",
                        os.XATTR_CREATE)
        os.setxattr(target, "user.color", b"red", os.XATTR_REPLACE)
        assert os.getxattr(target, "user.color") == b"red"
        os.removexattr(target, "user.shape")
        assert os.listxattr(target) == ["user.color"]
        with pytest.raises(OSError):
            os.getxattr(target, "user.shape")
        # xattrs persist in the filer entry itself
        e = fs.filer.find_entry("/d/renamed.txt")
        assert e.extended == {"user.color": b"red"}

        os.remove(mnt / "alias")
        os.remove(mnt / "hard.bin")
        os.remove(mnt / "d" / "renamed.txt")
        os.rmdir(mnt / "d")
        assert os.listdir(mnt) == []
    finally:
        conn.close()
