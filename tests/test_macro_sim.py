"""Macro-scale incident simulation: schema, determinism, invariants.

Tier-1 (unmarked) tests keep the fleet small (16 actors) so the whole
file runs in a few seconds; the 100-actor acceptance matrix — every
incident in the library at the paper-scale actor count — is
slow-marked. Alongside the sim proper this file pins down the control
policies the sim exercises with the REAL implementations on a virtual
clock: the circuit breaker's open -> half-open -> closed walk and the
adaptive limiter's dual-EWMA gradient on a scripted latency trace.
"""

import json

import pytest

from seaweedfs_tpu.qos.limiter import AdaptiveLimiter
from seaweedfs_tpu.sim.faults import FaultScheduler, parse_schedule
from seaweedfs_tpu.sim.harness import SimCluster
from seaweedfs_tpu.sim.incidents import INCIDENTS, run_incident
from seaweedfs_tpu.sim.workload import ZipfWorkload, default_tenants
from seaweedfs_tpu.utils import clockctl
from seaweedfs_tpu.utils.resilience import (CLOSED, HALF_OPEN, OPEN,
                                            CircuitBreaker)


# ---------------------------------------------------- fault schedule schema

def test_schedule_parses_json_and_dict_and_list():
    events = [{"link": "filer-0->vol-3", "fault": "latency",
               "start": 5.0, "duration": 10.0, "latency_ms": 250},
              {"link": "*->vol-7", "fault": "blackhole",
               "start": 8, "duration": 6}]
    for doc in (events, {"events": events},
                json.dumps({"events": events})):
        parsed = parse_schedule(doc)
        assert [e.fault for e in parsed] == ["latency", "blackhole"]
    # round-trips through to_dict
    again = parse_schedule([e.to_dict() for e in parse_schedule(events)])
    assert again[0].latency_ms == 250
    assert again[1].dst == "vol-7" and again[1].src == "*"


def test_schedule_rejects_malformed():
    with pytest.raises(ValueError):
        parse_schedule([{"link": "no-arrow", "fault": "latency",
                         "start": 0, "duration": 1}])
    with pytest.raises(ValueError):
        parse_schedule([{"link": "a->b", "fault": "meteor",
                         "start": 0, "duration": 1}])


def test_schedule_decide_stacks_latency_and_later_mode_wins():
    now = [0.0]
    sched = FaultScheduler(parse_schedule([
        {"link": "*->*", "fault": "latency", "start": 0, "duration": 10,
         "latency_ms": 100},
        {"link": "*->vol-1", "fault": "latency", "start": 0,
         "duration": 10, "latency_ms": 50},
        {"link": "*->vol-1", "fault": "http_error", "start": 5,
         "duration": 2, "status": 429},
    ]), lambda: now[0])
    mode, extra, _ = sched.decide("filer-0", "vol-1")
    assert mode is None and extra == pytest.approx(0.150)
    mode, extra, _ = sched.decide("filer-0", "vol-2")
    assert mode is None and extra == pytest.approx(0.100)
    now[0] = 6.0
    mode, extra, status = sched.decide("filer-0", "vol-1")
    assert mode == "http_error" and status == 429
    assert extra == pytest.approx(0.150)  # latency bands still stack
    now[0] = 12.0
    assert sched.decide("filer-0", "vol-1") == (None, 0.0, 503)
    assert sched.horizon() == 10.0


# ------------------------------------------------------------ determinism

def test_same_seed_same_event_log():
    a = run_incident("az_loss", seed=5, n_actors=16)
    b = run_incident("az_loss", seed=5, n_actors=16)
    assert a["log_hash"] == b["log_hash"]
    assert a["client"]["ops"] == b["client"]["ops"]
    c = run_incident("az_loss", seed=6, n_actors=16)
    assert c["log_hash"] != a["log_hash"]


def test_workload_is_a_pure_function_of_seed():
    spec = default_tenants(3, 60.0)
    ops1 = ZipfWorkload(spec, seed=11).generate(20.0)
    ops2 = ZipfWorkload(default_tenants(3, 60.0), seed=11).generate(20.0)
    assert [(o.t, o.tenant, o.kind, o.key) for o in ops1] == \
        [(o.t, o.tenant, o.kind, o.key) for o in ops2]
    # zipf skew: the most popular 1% of drawn keys covers a large
    # share of the draws (hot-spot traffic, not uniform)
    from collections import Counter
    counts = Counter(o.key for o in ops1)
    top = sum(n for _, n in counts.most_common(max(1, len(counts) // 100)))
    assert top / len(ops1) > 0.05


# ------------------------------------------------------ incident smokes

def test_rolling_restart_invisible_at_16_actors():
    r = run_incident("rolling_restart", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    assert r["client"]["failed"] == 0
    assert not r["repair"]["enqueued_for"]


def test_az_loss_converges_at_16_actors():
    r = run_incident("az_loss", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    assert r["repair"]["done"] > 0
    assert r["repair"]["converged_at"] is not None


def test_partition_heal_mid_repair_at_16_actors():
    r = run_incident("partition_heal_mid_repair", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    # partitioned ≠ crashed: every write during the window still acked
    assert r["client"]["failed"] == 0
    # the wave engaged (victims declared dead triggered repairs) and
    # the partition healed mid-flight, not after convergence
    assert r["repair"]["done"] > 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["repair_wave_engaged_before_heal"]["ok"]
    assert by_check["breakers_reclosed"]["ok"]


def test_ec_single_shard_loss_at_16_actors():
    r = run_incident("ec_single_shard_loss", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    # degraded reads fail over mid-repair, never fail outright
    assert r["client"]["failed"] == 0
    assert r["repair"]["done"] > 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["lrc_local_strategy_for_group_shards"]["ok"]
    assert by_check["lrc_read_cost_vs_rs"]["ok"]
    assert by_check["lrc_repair_bit_identical"]["ok"]


def test_hot_shard_migration_at_16_actors():
    r = run_incident("hot_shard_migration", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    # rolling_restart shape: the migration is invisible to clients
    assert r["client"]["failed"] == 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["planner_moved_hot_directory"]["ok"]
    assert by_check["hot_shard_share_collapsed"]["ok"]
    assert by_check["no_ping_pong"]["ok"]


def test_diurnal_sweep_at_16_actors():
    r = run_incident("diurnal_sweep", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    # the autopilot's whole day is invisible to clients
    assert r["client"]["failed"] == 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["cooled_set_reached_cloud"]["ok"]
    assert by_check["reheated_set_promoted_home"]["ok"]
    assert by_check["only_diurnal_set_moved"]["ok"]
    assert by_check["silence_paused_planner"]["ok"]
    assert by_check["no_ping_pong"]["ok"]


def test_master_failover_mid_write_at_16_actors():
    r = run_incident("master_failover_mid_write", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    # the headline: a 6s leader outage under a write flood costs
    # nothing — every fid minted from a holder's lease
    assert r["client"]["failed"] == 0
    assert r["client"]["assign"]["leased"] > 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["writes_minted_during_outage"]["ok"]
    assert by_check["leader_took_over"]["ok"]
    assert by_check["no_spurious_repairs"]["ok"]


def test_master_failover_mid_repair_at_16_actors():
    r = run_incident("master_failover_mid_repair", seed=0, n_actors=16)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]
    assert r["repair"]["done"] > 0
    by_check = {c["name"]: c for c in r["invariants"]}
    assert by_check["repair_wave_engaged_before_failover"]["ok"]
    assert by_check["no_duplicate_rebuilds"]["ok"]
    assert by_check["repair_wave_settled"]["ok"]


def test_comparator_lane_off_routes_assigns_to_master():
    """assign_leases=False is the pre-lease protocol: every write pays
    the master round trip, and a leader outage would stall them."""
    cluster = SimCluster(n_volume_actors=8, n_az=4, seed=3,
                         assign_leases=False)
    wl = ZipfWorkload(default_tenants(2, 40.0), seed=3)
    cluster.load(wl.generate(8.0))
    cluster.run(10.0)
    assert cluster.metrics.master_assigns > 0
    assert cluster.metrics.lease_mints == 0
    assert cluster.metrics.fail_total == 0
    # and with the lane on (default), the same fleet mints locally
    cluster2 = SimCluster(n_volume_actors=8, n_az=4, seed=3)
    wl2 = ZipfWorkload(default_tenants(2, 40.0), seed=3)
    cluster2.load(wl2.generate(8.0))
    cluster2.run(10.0)
    assert cluster2.metrics.lease_mints > 0
    assert cluster2.metrics.master_assigns == 0


def test_unknown_incident_raises():
    with pytest.raises(KeyError):
        run_incident("kraken", n_actors=16)


def test_sim_drain_excludes_node_and_finishes_inflight():
    cluster = SimCluster(n_volume_actors=8, n_az=4, seed=1)
    wl = ZipfWorkload(default_tenants(2, 40.0), seed=1)
    cluster.load(wl.generate(8.0))
    cluster.at(2.0, cluster.drain, "vol-0")
    cluster.run(12.0)
    actor = cluster.actor("vol-0")
    assert actor.draining and actor.crashed  # drain ran to completion
    assert actor.active == 0                 # nothing left in flight
    st = cluster.master.nodes["vol-0"]
    assert st["draining"]
    # the master granted drain grace instead of queueing repairs
    assert cluster.master.drain_grace_until.get("vol-0", 0) > 0
    assert not cluster.master.repair_enqueued_for


def test_az_disjoint_placement_requires_enough_zones():
    with pytest.raises(ValueError):
        SimCluster(n_volume_actors=8, n_az=2, replication=3)
    c = SimCluster(n_volume_actors=8, n_az=4, replication=3)
    for vid, holders in c.master.layout.items():
        azs = {c.actor(h).az for h in holders}
        assert len(azs) == 3  # one replica per zone


# ------------------------------------- real policies on the virtual clock

def test_breaker_walks_open_half_open_closed_on_virtual_time():
    t = [0.0]
    with clockctl.install(lambda: t[0]):
        br = CircuitBreaker(failure_threshold=3, open_for=2.0)
        for _ in range(3):
            br.record(False)
        assert br.state == OPEN and not br.allow()
        t[0] += 1.0
        assert not br.probe_ripe() and not br.allow()
        t[0] += 1.1  # open_for elapsed: one probe slot opens
        assert br.probe_ripe()
        assert br.allow()
        assert br.state == HALF_OPEN
        assert not br.allow()  # probe slots metered (half_open_max=1)
        br.record(True, 0.004)
        assert br.state == CLOSED and br.allow()


def test_breaker_failed_probe_rearms_full_window():
    t = [0.0]
    with clockctl.install(lambda: t[0]):
        br = CircuitBreaker(failure_threshold=1, open_for=2.0)
        br.record(False)
        t[0] += 2.5
        assert br.allow()      # half-open probe
        br.record(False)       # probe fails: re-open, fresh clock
        assert br.state == OPEN
        t[0] += 1.0            # only half the window
        assert not br.allow()
        t[0] += 1.5
        assert br.allow()


def test_adaptive_limiter_gradient_on_scripted_trace():
    def make():
        return AdaptiveLimiter(initial=32, min_limit=8, max_limit=256)

    lim = make()
    # scripted trace, phase 1: steady 4ms service -> headroom, the
    # limit climbs (gradient clamps at 1.1 plus the sqrt explore term)
    for _ in range(200):
        lim.observe(0.004)
    grown = lim.limit
    assert grown > 32
    assert lim.queue_delay() == pytest.approx(0.0, abs=1e-9)
    # phase 2: latency steps to 40ms — the short EWMA races ahead of
    # the long baseline, the gradient drops below 1, the limit backs off
    for _ in range(50):
        lim.observe(0.040)
    assert lim.queue_delay() > 0.010
    assert lim.limit < grown
    # the whole walk is deterministic: an identical twin fed the same
    # trace lands on the identical limit
    twin = make()
    for _ in range(200):
        twin.observe(0.004)
    for _ in range(50):
        twin.observe(0.040)
    assert twin.snapshot() == lim.snapshot()


def test_scrub_token_bucket_elapses_on_virtual_time():
    """The scrubber's byte throttle (TokenBucket) rides clockctl: under
    a virtual clock its refills and waits follow the sim timeline, so
    consuming 400 bytes at 100 B/s costs 4 virtual seconds and ~zero
    wall seconds — the property that lets the macro-sim model scrub
    pacing without wall-clock sleeps."""
    import time as _time

    from seaweedfs_tpu.utils.limiter import TokenBucket

    t = [0.0]
    with clockctl.install(lambda: t[0],
                          sleep_fn=lambda s: t.__setitem__(0, t[0] + s)):
        tb = TokenBucket(rate_bytes_per_sec=100.0)
        wall0 = _time.perf_counter()
        for _ in range(4):
            assert tb.consume(100)
        wall = _time.perf_counter() - wall0
    # the bucket starts empty, so 4x100 bytes is exactly 4s of refill
    assert t[0] == pytest.approx(4.0)
    assert wall < 0.5


def test_token_bucket_refuses_to_block_inside_the_sim():
    """install() without a sleep hook (how the sim kernel runs) makes a
    limiter that would block raise instead of stalling the one real
    thread the whole fleet shares."""
    from seaweedfs_tpu.utils.limiter import TokenBucket

    with clockctl.install(lambda: 0.0):
        tb = TokenBucket(rate_bytes_per_sec=10.0)
        with pytest.raises(RuntimeError, match="virtual clock"):
            tb.consume(100)


# ------------------------- same schedule schema against real processes

def test_netchaos_replays_sim_schedule_against_real_proxy():
    import time as _time

    from tools.netchaos import ChaosProxy, ScheduleDriver
    from seaweedfs_tpu.utils.httpd import HttpServer, Response, http_call

    srv = HttpServer()
    srv.add("GET", "/ping", lambda req: Response({"ok": True}))
    srv.start()
    proxy = ChaosProxy("127.0.0.1", srv.port).start()
    # the exact JSON the sim transport consumes, replayed on wall time
    driver = ScheduleDriver(proxy, {"events": [
        {"link": "*->*", "fault": "http_error", "start": 0.1,
         "duration": 0.4, "status": 418}]}).start()
    try:
        deadline = _time.time() + 2.0
        saw_fault = False
        while _time.time() < deadline and not saw_fault:
            status, _, _ = http_call("GET", f"http://{proxy.url}/ping",
                                     timeout=2.0)
            saw_fault = status == 418
            _time.sleep(0.05)
        assert saw_fault
        deadline = _time.time() + 3.0
        while _time.time() < deadline and not driver.done():
            _time.sleep(0.05)
        assert driver.done()  # schedule exhausted, proxy healed
        status, _, _ = http_call("GET", f"http://{proxy.url}/ping",
                                 timeout=2.0)
        assert status == 200
        modes = [a["mode"] for a in driver.applied]
        assert "http_error" in modes and modes[-1] == "pass"
    finally:
        driver.stop()
        proxy.stop()
        srv.stop()


# ------------------------------------------------- 100-actor acceptance

@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(INCIDENTS))
def test_incident_matrix_100_actors(name):
    r = run_incident(name, seed=0, n_actors=100)
    assert r["passed"], [c for c in r["invariants"] if not c["ok"]]


@pytest.mark.slow
def test_bit_reproducible_at_100_actors():
    a = run_incident("rolling_restart", seed=42, n_actors=100)
    b = run_incident("rolling_restart", seed=42, n_actors=100)
    assert a["log_hash"] == b["log_hash"]
