"""etcd-protocol FilerStore over gRPC (reference
weed/filer/etcd/etcd_store.go, SDK-based there; here the public
etcdserverpb.KV wire API — Range/Put/DeleteRange with etcd's real
package and field numbers — is spoken directly against MiniEtcdServer,
so the framing a stock etcd expects is exercised end-to-end)."""

import time

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry
from seaweedfs_tpu.filer.etcd_store import (EtcdClient, EtcdFilerStore,
                                            MiniEtcdServer)
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call


@pytest.fixture
def etcd():
    srv = MiniEtcdServer().start()
    yield srv
    srv.stop()


def test_kv_wire_protocol(etcd):
    c = EtcdClient(f"127.0.0.1:{etcd.port}")
    c.put(b"/k1", b"v1")
    c.put(b"/k2", b"v2")
    c.put(b"/k3", b"v3")
    assert c.range(b"/k1") == [(b"/k1", b"v1")]
    assert c.range(b"/nope") == []
    # half-open range + limit
    assert c.range(b"/k1", b"/k3") == [(b"/k1", b"v1"), (b"/k2", b"v2")]
    assert c.range(b"/k1", b"/k9", limit=2) == [(b"/k1", b"v1"),
                                                (b"/k2", b"v2")]
    assert c.delete_range(b"/k1", b"/k3") == 2
    assert c.range(b"/k1", b"/k9") == [(b"/k3", b"v3")]
    c.close()


def test_etcd_store_contract(etcd):
    """The same contract the embedded and redis stores pass."""
    s = make_store("etcd", host="127.0.0.1", port=etcd.port)
    assert isinstance(s, EtcdFilerStore)
    e = Entry("/a/b/file.txt", Attr(mtime=1.0, file_size=5))
    s.insert_entry(e)
    got = s.find_entry("/a/b/file.txt")
    assert got is not None and got.attr.file_size == 5

    s.insert_entry(Entry("/a/b/other.txt"))
    s.insert_entry(Entry("/a/b/sub", Attr(is_directory=True)))
    s.insert_entry(Entry("/a/b/sub/deep.txt"))
    # a sibling directory sharing the prefix must never be swallowed
    s.insert_entry(Entry("/a/bb/cousin.txt"))
    names = [x.name for x in s.list_directory_entries("/a/b")]
    assert names == ["file.txt", "other.txt", "sub"]
    names = [x.name for x in s.list_directory_entries("/a/b", prefix="o")]
    assert names == ["other.txt"]
    names = [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt")]
    assert names == ["other.txt", "sub"]
    names = [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt", include_start=True)]
    assert names == ["file.txt", "other.txt", "sub"]
    assert [x.name for x in s.list_directory_entries("/a/b", limit=2)] \
        == ["file.txt", "other.txt"]

    s.delete_folder_children("/a/b")
    assert s.list_directory_entries("/a/b") == []
    assert s.find_entry("/a/b/sub/deep.txt") is None  # recursive
    assert s.find_entry("/a/bb/cousin.txt") is not None  # untouched

    s.kv_put(b"conf", b"xyz")
    assert s.kv_get(b"conf") == b"xyz"
    assert s.kv_get(b"missing") is None
    s.kv_delete(b"conf")
    assert s.kv_get(b"conf") is None
    s.close()


def test_filer_server_on_etcd_store(etcd, tmp_path):
    """Full filer (HTTP plane + chunking) with etcd metadata; an
    independent client sees the same entries over the wire."""
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url, store="etcd",
                     store_dir=f"127.0.0.1:{etcd.port}")
    fs.start()
    time.sleep(0.1)
    try:
        payload = b"stored through etcd metadata" * 300
        status, _, _ = http_call("POST", f"http://{fs.url}/dir/doc.bin",
                                 body=payload)
        assert status < 300
        status, body, _ = http_call("GET", f"http://{fs.url}/dir/doc.bin")
        assert status == 200 and body == payload

        other = EtcdFilerStore("127.0.0.1", etcd.port)
        e = other.find_entry("/dir/doc.bin")
        assert e is not None and e.file_size() == len(payload)
        assert e.chunks
        other.close()

        status, _, _ = http_call(
            "POST", f"http://{fs.url}/__api/rename",
            json_body={"from": "/dir/doc.bin", "to": "/dir/doc2.bin"})
        assert status == 200
        status, body, _ = http_call("GET",
                                    f"http://{fs.url}/dir/doc2.bin")
        assert status == 200 and body == payload
        status, _, _ = http_call("DELETE",
                                 f"http://{fs.url}/dir/doc2.bin")
        assert status < 300
        status, _, _ = http_call("GET", f"http://{fs.url}/dir/doc2.bin")
        assert status == 404
    finally:
        fs.stop()
        vs.stop()
        master.stop()


def test_mesh_mtls_does_not_leak_onto_etcd_channel(etcd, tmp_path,
                                                   monkeypatch):
    """Review finding: etcd is an external system — the cluster's
    [grpc] mesh certs must not be presented to it (a stock etcd would
    reject them). Only a dedicated [grpc.etcd] section opts in."""
    from seaweedfs_tpu.utils import config as _cfg
    (tmp_path / "security.toml").write_text(
        '[grpc]\nca = "/no/ca.pem"\ncert = "/no/c.pem"\n'
        'key = "/no/k.pem"\n')
    monkeypatch.setattr(_cfg, "SEARCH_PATHS", [str(tmp_path)])
    c = EtcdClient(f"127.0.0.1:{etcd.port}")  # would crash if it read certs
    c.put(b"/x", b"1")
    assert c.range(b"/x") == [(b"/x", b"1")]
    c.close()


def test_large_directory_pagination(etcd):
    """Listing pages through the range API in batches (the client asks
    for at most 1024 keys per Range)."""
    s = make_store("etcd", host="127.0.0.1", port=etcd.port)
    for i in range(1500):
        s.insert_entry(Entry(f"/big/f{i:05d}"))
    names = [x.name for x in s.list_directory_entries("/big",
                                                      limit=1 << 20)]
    assert len(names) == 1500
    assert names == sorted(names)
    # resume mid-way like the filer's paged listings do
    page = [x.name for x in s.list_directory_entries(
        "/big", start_name="f01000", limit=10)]
    assert page == [f"f{i:05d}" for i in range(1001, 1011)]
    s.close()
