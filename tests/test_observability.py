"""Observability floor (round-5 verdict item 3): glog-style leveled
logging wired through the servers (reference weed/glog/glog.go) and
metrics parity — /metrics on all four server types plus the
push-gateway loop (reference weed/stats/metrics.go:226-262)."""

import re
import threading
import time

import pytest

from seaweedfs_tpu.utils import glog


@pytest.fixture(autouse=True)
def _reset_glog():
    yield
    glog.reset()


def test_glog_line_format_and_levels(tmp_path):
    log = tmp_path / "weed.log"
    glog.set_log_file(str(log), also_stderr=False)
    glog.info("hello %s", "world")
    glog.warning("watch out")
    glog.error("boom %d", 7)
    lines = log.read_text().splitlines()
    assert len(lines) == 3
    # glog header: I0730 14:03:02.123456 <tid> <file>:<line>] msg
    assert re.match(
        r"I\d{4} \d\d:\d\d:\d\d\.\d{6}\s+\d+ test_observability\.py:\d+\] "
        r"hello world", lines[0])
    assert lines[1].startswith("W") and "watch out" in lines[1]
    assert lines[2].startswith("E") and "boom 7" in lines[2]


def test_glog_verbosity_and_vmodule(tmp_path):
    log = tmp_path / "weed.log"
    glog.set_log_file(str(log), also_stderr=False)
    assert not glog.v(1)
    glog.vlog(1, "hidden")
    glog.set_verbosity(2)
    assert glog.v(2) and not glog.v(3)
    glog.vlog(2, "shown")
    # vmodule override beats the global level for this module
    glog.set_vmodule("test_observability=0")
    assert not glog.v(1)
    glog.set_vmodule("test_*=3")
    assert glog.v(3)
    text = log.read_text()
    assert "hidden" not in text and "shown" in text


def test_glog_rotation(tmp_path):
    log = tmp_path / "weed.log"
    glog.set_log_file(str(log), max_bytes=400, also_stderr=False)
    for i in range(40):
        glog.info("filler line %03d with some padding", i)
    rotated = [p for p in tmp_path.iterdir()
               if p.name.startswith("weed.log.")]
    assert rotated, "no rotated log files appeared"
    assert log.exists()


def test_fatal_raises_and_logs(tmp_path):
    log = tmp_path / "weed.log"
    glog.set_log_file(str(log), also_stderr=False)
    with pytest.raises(SystemExit):
        glog.fatal("unrecoverable %s", "state")
    assert "unrecoverable state" in log.read_text()


@pytest.fixture
def stack(tmp_path):
    from seaweedfs_tpu.gateway.s3_server import S3Server
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    ms = MasterServer(volume_size_limit_mb=64)
    ms.start()
    vs = VolumeServer([str(tmp_path / "v")], ms.url)
    vs.start()
    time.sleep(0.3)
    fs = FilerServer(ms.url)
    fs.start()
    s3 = S3Server(fs)
    s3.start()
    yield ms, vs, fs, s3
    s3.stop()
    fs.stop()
    vs.stop()
    ms.stop()


def test_metrics_on_all_four_servers(stack):
    """Boot the full stack, drive one write through filer and S3, then
    scrape all four /metrics endpoints."""
    import urllib.request

    from seaweedfs_tpu.utils.httpd import http_call
    ms, vs, fs, s3 = stack
    status, _, _ = http_call("POST", f"http://{fs.url}/obs/a.txt",
                             body=b"x" * 4096)
    assert status < 300
    urllib.request.urlopen(f"http://{fs.url}/obs/a.txt").read()
    status, _, _ = http_call("PUT", f"http://{s3.url}/obsbkt")
    assert status < 300
    status, _, _ = http_call("PUT", f"http://{s3.url}/obsbkt/k",
                             body=b"s3 body")
    assert status < 300

    def scrape(url):
        return urllib.request.urlopen(f"http://{url}").read().decode()

    master_m = scrape(f"{ms.url}/metrics")
    assert "SeaweedFS_TPU_master_data_nodes 1" in master_m
    assert "SeaweedFS_TPU_master_is_leader 1.0" in master_m
    assert "SeaweedFS_TPU_master_volumes" in master_m
    volume_m = scrape(f"{vs.url}/metrics")
    assert "SeaweedFS_TPU_volumeServer_volumes" in volume_m
    assert "SeaweedFS_TPU_volumeServer_disk_free_bytes" in volume_m
    assert 'request_total{type="write"}' in volume_m
    # filer metrics ride a dedicated listener (reference -metricsPort)
    # so a user file stored at /metrics stays reachable on the main port
    filer_m = scrape(f"{fs.metrics_url}/metrics")
    assert 'SeaweedFS_TPU_filer_request_total{type="write"} 1' in filer_m
    assert 'SeaweedFS_TPU_filer_request_total{type="read"} 1' in filer_m
    assert "SeaweedFS_TPU_filer_request_seconds_bucket" in filer_m
    # s3 metrics also ride a dedicated listener: the public port is
    # all unvalidated bucket namespace and the exposition would leak
    # bucket names to unauthenticated clients
    s3_m = scrape(f"{s3.metrics_url}/metrics")
    assert ('SeaweedFS_TPU_s3_request_total'
            '{action="Write",bucket="obsbkt"} 1') in s3_m
    assert "SeaweedFS_TPU_s3_request_seconds_count" in s3_m


def test_v2_emits_request_lines(stack, tmp_path):
    import urllib.request
    ms, _, _, _ = stack
    log = tmp_path / "req.log"
    glog.set_log_file(str(log), also_stderr=False)
    glog.set_verbosity(2)
    urllib.request.urlopen(f"http://{ms.url}/cluster/status").read()
    deadline = time.time() + 2
    while time.time() < deadline:
        if "/cluster/status" in log.read_text():
            break
        time.sleep(0.05)
    line = next(ln for ln in log.read_text().splitlines()
                if "/cluster/status" in ln)
    # method path status bytes duration
    assert re.search(r"GET /cluster/status 200 \d+B [\d.]+ms", line)


def test_handler_exceptions_logged_with_traceback(tmp_path):
    from seaweedfs_tpu.utils.httpd import HttpServer, http_call
    log = tmp_path / "err.log"
    glog.set_log_file(str(log), also_stderr=False)
    srv = HttpServer()

    def explode(req):
        raise RuntimeError("kaboom")

    srv.add("GET", "/boom", explode)
    srv.start()
    try:
        status, body, _ = http_call(
            "GET", f"http://{srv.host}:{srv.port}/boom")
        assert status == 500 and b"kaboom" in body
    finally:
        srv.stop()
    text = log.read_text()
    assert "handler error" in text
    assert "RuntimeError" in text and "explode" in text  # traceback


def test_push_includes_scrape_time_gauges(stack):
    # the push loop calls expose_text() directly; the on_expose hooks
    # must refresh topology gauges there too, not only in the HTTP
    # scrape handler
    ms, _, _, _ = stack
    text = ms.metrics.expose_text()
    assert "SeaweedFS_TPU_master_data_nodes 1" in text
    assert "SeaweedFS_TPU_master_is_leader 1.0" in text


def test_push_gateway_loop(tmp_path):
    from seaweedfs_tpu.utils.httpd import HttpServer, Response
    from seaweedfs_tpu.utils.metrics import Registry
    got = []
    gw = HttpServer()
    gw.add("PUT", "/metrics/job/.*",
           lambda req: (got.append((req.path, req.body)),
                        Response({}))[1])
    gw.start()
    try:
        reg = Registry()
        c = reg.counter("test", "pushed_total", "x")
        c.inc()
        reg.start_push(f"{gw.host}:{gw.port}", "volumeServer",
                       "127.0.0.1:8080", interval_sec=0.1)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.02)
        reg.stop_push()
        assert got, "no push arrived"
        path, body = got[0]
        assert path.startswith("/metrics/job/volumeServer/instance/")
        assert b"SeaweedFS_TPU_test_pushed_total 1.0" in body
    finally:
        gw.stop()
