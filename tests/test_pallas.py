"""Pallas coder bit-identity (interpret mode on the CPU mesh)."""

import numpy as np

from seaweedfs_tpu.models.coder import RSScheme, make_coder


def test_pallas_encode_matches_cpu():
    rng = np.random.default_rng(0)
    cpu = make_coder("cpu")
    pal = make_coder("pallas")
    data = rng.integers(0, 256, (10, 8192), dtype=np.uint8)
    assert np.array_equal(pal.encode_array(data), cpu.encode_array(data))


def test_pallas_unaligned_and_bytes_api():
    rng = np.random.default_rng(1)
    cpu = make_coder("cpu")
    pal = make_coder("pallas")
    data = [rng.integers(0, 256, 5001, dtype=np.uint8).tobytes()
            for _ in range(10)]
    a = cpu.encode(data)
    b = pal.encode(data)
    assert all(x == y for x, y in zip(a, b))

    # reconstruct path (inherited jnp decode) still bit-identical
    shards = [None if i in (0, 13) else a[i] for i in range(14)]
    assert pal.reconstruct(shards) == cpu.reconstruct(list(shards))


def test_mxu_bitplane_coder_matches_cpu():
    """The fused MXU bitplane kernel (interpret mode on CPU) is
    bit-identical to the CPU coder — the measurement in ops/rs_mxu.py's
    docstring is of a correct kernel."""
    rng = np.random.default_rng(2)
    cpu = make_coder("cpu")
    mxu = make_coder("mxu")
    data = rng.integers(0, 256, (10, 8192), dtype=np.uint8)
    assert np.array_equal(mxu.encode_array(data), cpu.encode_array(data))
    data2 = [rng.integers(0, 256, 5001, dtype=np.uint8).tobytes()
             for _ in range(10)]
    assert cpu.encode(data2) == mxu.encode(data2)
