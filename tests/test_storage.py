"""Needle codec + volume lifecycle tests (reference-style: real temp files,
byte-level round trips; see weed/storage/needle/needle_read_test.go and
volume_vacuum_test.go for the models)."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import (CURRENT_VERSION, CrcError, Needle,
                                          VERSION1, VERSION2)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock, TTL
from seaweedfs_tpu.storage.volume import (DeletedError, NotFoundError, Volume,
                                          CookieMismatchError)


def test_needle_roundtrip_v3():
    n = Needle(id=0x1234, cookie=0xDEADBEEF, data=b"hello world",
               name=b"f.txt", mime=b"text/plain", last_modified=1700000000,
               pairs=b'{"a":"b"}')
    n.set_flags_from_fields()
    n.append_at_ns = 123456789
    rec = n.to_bytes(CURRENT_VERSION)
    assert len(rec) % t.NEEDLE_PADDING_SIZE == 0
    m = Needle.from_bytes(rec, n.size, CURRENT_VERSION)
    assert (m.id, m.cookie, m.data) == (n.id, n.cookie, b"hello world")
    assert m.name == b"f.txt" and m.mime == b"text/plain"
    assert m.last_modified == 1700000000
    assert m.pairs == b'{"a":"b"}'
    assert m.append_at_ns == 123456789


@pytest.mark.parametrize("version", [VERSION1, VERSION2, CURRENT_VERSION])
def test_needle_versions(version):
    n = Needle(id=7, cookie=99, data=b"x" * 100)
    n.set_flags_from_fields()
    rec = n.to_bytes(version)
    assert len(rec) % 8 == 0
    m = Needle.from_bytes(rec, n.size, version)
    assert m.data == b"x" * 100


def test_needle_crc_detects_corruption():
    n = Needle(id=1, cookie=2, data=b"payload")
    rec = bytearray(n.to_bytes(CURRENT_VERSION))
    rec[t.NEEDLE_HEADER_SIZE + 5] ^= 0xFF  # flip a data byte
    with pytest.raises(CrcError):
        Needle.from_bytes(bytes(rec), n.size, CURRENT_VERSION)


def test_empty_needle_is_deletion_record():
    n = Needle(id=5, cookie=1)
    rec = n.to_bytes(CURRENT_VERSION)
    assert n.size == 0
    m = Needle.from_bytes(rec, 0, CURRENT_VERSION)
    assert m.data == b""


def test_file_id():
    f = FileId(3, 0x1234, 0xABCD1234)
    assert str(f) == "3,1234abcd1234"
    g = FileId.parse("3,1234abcd1234")
    assert g == f
    h = FileId.parse("7,2c4a8d9e12345678.jpg")
    assert h.volume_id == 7 and h.cookie == 0x12345678


def test_superblock_roundtrip():
    sb = SuperBlock(version=3, replica_placement=ReplicaPlacement.parse("012"),
                    ttl=TTL.parse("3d"), compaction_revision=7)
    b = sb.to_bytes()
    assert len(b) == 8
    sb2 = SuperBlock.parse(b)
    assert sb2.version == 3
    assert str(sb2.replica_placement) == "012"
    assert str(sb2.ttl) == "3d"
    assert sb2.compaction_revision == 7


def test_volume_write_read_delete(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    n = Needle(id=0x10, cookie=0x42, data=b"alpha", name=b"a.txt")
    n.set_flags_from_fields()
    v.write_needle(n)
    v.write_needle(Needle(id=0x11, cookie=0x43, data=b"beta" * 100))

    got = v.read_needle(0x10, cookie=0x42)
    assert got.data == b"alpha" and got.name == b"a.txt"
    with pytest.raises(CookieMismatchError):
        v.read_needle(0x10, cookie=0x99)
    with pytest.raises(NotFoundError):
        v.read_needle(0xFF)

    freed = v.delete_needle(0x10)
    assert freed > 0
    with pytest.raises((NotFoundError, DeletedError)):
        v.read_needle(0x10)
    assert v.delete_needle(0x10) == 0  # idempotent
    v.close()


def test_volume_reload_replays_idx(tmp_path):
    v = Volume(str(tmp_path), "", 2)
    for i in range(20):
        v.write_needle(Needle(id=i + 1, cookie=7, data=bytes([i]) * (i + 1)))
    v.delete_needle(5)
    v.close()

    v2 = Volume(str(tmp_path), "", 2)
    assert v2.read_needle(1, cookie=7).data == b"\x00"
    assert v2.read_needle(20).data == bytes([19]) * 20
    with pytest.raises((NotFoundError, DeletedError)):
        v2.read_needle(5)
    assert v2.check_integrity()
    v2.close()


def test_volume_compact_reclaims_space(tmp_path):
    v = Volume(str(tmp_path), "", 3)
    rng = np.random.default_rng(0)
    payloads = {}
    for i in range(30):
        data = rng.integers(0, 256, 200, dtype=np.uint8).tobytes()
        payloads[i + 1] = data
        v.write_needle(Needle(id=i + 1, cookie=1, data=data))
    for i in range(1, 21):
        v.delete_needle(i)
        payloads.pop(i)
    before = v.content_size()
    assert v.garbage_level() > 0.3
    v.compact()
    after = v.content_size()
    assert after < before
    assert v.super_block.compaction_revision == 1
    for nid, data in payloads.items():
        assert v.read_needle(nid).data == data
    with pytest.raises((NotFoundError, DeletedError)):
        v.read_needle(1)
    v.close()


def test_volume_collection_naming(tmp_path):
    v = Volume(str(tmp_path), "photos", 9)
    assert os.path.basename(v.file_name()) == "photos_9"
    v.close()
