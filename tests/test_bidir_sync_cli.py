"""Bidirectional filer.sync with signature-based echo suppression
(reference command/filer_sync.go signatures) and the round-4 CLI
subcommands (filer.cat/copy/meta.backup, version)."""

import json
import time

import pytest

from seaweedfs_tpu.cli import main as cli_main
from seaweedfs_tpu.replication.sync import BidirectionalSync
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call, http_json


@pytest.fixture
def two_filers(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    a = FilerServer(master.url)
    b = FilerServer(master.url)
    a.start()
    b.start()
    time.sleep(0.1)
    yield master, a, b
    b.stop()
    a.stop()
    vs.stop()
    master.stop()


def _wait_for(fn, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


def test_bidirectional_sync_no_echo(two_filers):
    master, a, b = two_filers
    sync = BidirectionalSync(a.url, b.url)
    sync.start()
    try:
        # A-side write replicates to B
        http_call("POST", f"http://{a.url}/docs/from_a.txt", body=b"AAA")
        assert _wait_for(lambda: http_call(
            "GET", f"http://{b.url}/docs/from_a.txt")[0] == 200)
        # B-side write replicates to A — active-active
        http_call("POST", f"http://{b.url}/docs/from_b.txt", body=b"BBB")
        assert _wait_for(lambda: http_call(
            "GET", f"http://{a.url}/docs/from_b.txt")[0] == 200)

        # no echo: the event logs stop growing once both sides settle
        time.sleep(1.0)
        counts = (len(a.filer.meta_log.events),
                  len(b.filer.meta_log.events))
        time.sleep(1.5)
        assert (len(a.filer.meta_log.events),
                len(b.filer.meta_log.events)) == counts, \
            "event logs still growing: replication is echoing"

        # updates propagate too (and still don't echo)
        http_call("POST", f"http://{a.url}/docs/from_a.txt", body=b"A2")
        assert _wait_for(lambda: http_call(
            "GET", f"http://{b.url}/docs/from_a.txt")[1] == b"A2")

        # deletes propagate
        http_call("DELETE", f"http://{b.url}/docs/from_a.txt")
        assert _wait_for(lambda: http_call(
            "GET", f"http://{a.url}/docs/from_a.txt")[0] == 404)
    finally:
        sync.stop()


def test_sync_signature_tagging(two_filers):
    """Writes carrying X-Weed-Sync-Signature surface the tag in the
    event stream, and exclude_signature filters exactly those."""
    master, a, b = two_filers
    http_call("POST", f"http://{a.url}/p/mine.txt", body=b"x")
    http_call("POST", f"http://{a.url}/p/theirs.txt", body=b"y",
              headers={"X-Weed-Sync-Signature": "777"})
    out = http_json("GET",
                    f"http://{a.url}/__api/meta_events?since_ns=0")
    sigs = {e["new_entry"]["full_path"]: e.get("signature", 0)
            for e in out["events"] if e.get("new_entry")}
    assert sigs["/p/mine.txt"] == 0
    assert sigs["/p/theirs.txt"] == 777
    out = http_json(
        "GET", f"http://{a.url}/__api/meta_events?since_ns=0"
               f"&exclude_signature=777")
    paths = [e["new_entry"]["full_path"] for e in out["events"]
             if e.get("new_entry")]
    assert "/p/mine.txt" in paths and "/p/theirs.txt" not in paths


def test_excluded_burst_does_not_starve_reader(two_filers):
    """Review finding: >= 1024 consecutive replicated (excluded) events
    must not hide the native events behind them, and the poll cursor
    must advance past an all-excluded scan."""
    master, a, b2 = two_filers
    for i in range(1100):
        http_call("POST", f"http://{b2.url}/bulk/g{i:04d}", body=b"y",
                  headers={"X-Weed-Sync-Signature": "555"})
    http_call("POST", f"http://{b2.url}/bulk/native.txt", body=b"mine")
    out = http_json("GET", f"http://{b2.url}/__api/meta_events"
                           f"?since_ns=0&exclude_signature=555")
    paths = [(e.get("new_entry") or {}).get("full_path")
             for e in out["events"]]
    assert "/bulk/native.txt" in paths, \
        "native event starved behind the excluded burst"
    assert not any(p and p.startswith("/bulk/g") for p in paths)
    # an all-excluded window advances the cursor instead of stalling
    native_ts = next(e["tsns"] for e in out["events"]
                     if (e.get("new_entry") or {}).get("full_path")
                     == "/bulk/native.txt")
    out2 = http_json("GET", f"http://{b2.url}/__api/meta_events"
                            f"?since_ns={native_ts}"
                            f"&exclude_signature=555")
    assert out2["events"] == []
    assert out2["cursor"] >= native_ts


def test_aggregated_stream_keeps_signature(two_filers):
    """Review finding: the aggregator must carry the signature through
    the merge or aggregated-stream exclusion silently no-ops."""
    master, a, b = two_filers
    if getattr(a, "meta_aggregator", None) is None:
        pytest.skip("aggregator not running on this fixture")
    http_call("POST", f"http://{a.url}/agg/tagged.txt", body=b"t",
              headers={"X-Weed-Sync-Signature": "909"})
    deadline = time.time() + 5
    while time.time() < deadline:
        evs = a.meta_aggregator.log.read_since(0, "/agg")
        if evs:
            break
        time.sleep(0.05)
    assert evs and evs[-1].get("signature") == 909
    assert a.meta_aggregator.log.read_since(
        0, "/agg", exclude_signature=909) == []


def test_cli_version(capsys):
    cli_main(["version"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["version"] and out["python"]


def test_cli_filer_cat_copy_meta_backup(two_filers, tmp_path, capsys):
    master, a, b = two_filers
    # filer.copy: local tree -> filer
    src = tmp_path / "local"
    (src / "sub").mkdir(parents=True)
    (src / "one.txt").write_bytes(b"first")
    (src / "sub" / "two.txt").write_bytes(b"second")
    cli_main(["filer.copy", "-filer", a.url, str(src / "one.txt"),
              str(src / "sub"), "/in/"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["copied"] == 2

    # filer.cat prints the copied bytes
    cli_main(["filer.cat", "-filer", a.url, "/in/one.txt"])
    assert capsys.readouterr().out.encode().strip() == b"first"

    # filer.meta.backup dumps the event log
    dump = tmp_path / "meta.jsonl"
    cli_main(["filer.meta.backup", "-filer", a.url, "-o", str(dump)])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["events"] >= 2
    lines = [json.loads(l) for l in dump.read_text().splitlines()]
    assert any((l.get("new_entry") or {}).get("full_path")
               == "/in/one.txt" for l in lines)
