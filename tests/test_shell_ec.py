"""Shell planner unit tests (pure, no cluster — the reference's strategy in
command_ec_test.go) + the full distributed EC lifecycle over an in-process
cluster: encode -> spread -> degraded read -> rebuild -> decode."""

import time

import numpy as np
import pytest

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell import ec_plan
from seaweedfs_tpu.shell.commands import ShellContext
from seaweedfs_tpu.storage.erasure_coding import layout


def _node(node_id, rack="r1", dc="dc1", maxv=8, volumes=(), ec=()):
    return {"id": node_id, "rack": rack, "data_center": dc,
            "max_volume_count": maxv, "volumes": list(volumes),
            "ec_shards": list(ec)}


def _topo(nodes):
    racks = {}
    for n in nodes:
        racks.setdefault((n["data_center"], n["rack"]), []).append(n)
    dcs = {}
    for (dc, rack), ns in racks.items():
        dcs.setdefault(dc, []).append({"id": rack, "nodes": ns})
    return {"data_centers": [{"id": dc, "racks": rs}
                             for dc, rs in dcs.items()]}


def test_balanced_distribution_round_robin():
    nodes = [ec_plan.EcNode("a", 100), ec_plan.EcNode("b", 100),
             ec_plan.EcNode("c", 100)]
    targets = ec_plan.balanced_ec_distribution(nodes)
    assert len(targets) == 14
    counts = {t: targets.count(t) for t in set(targets)}
    assert max(counts.values()) - min(counts.values()) <= 1


def test_balanced_distribution_prefers_free():
    nodes = [ec_plan.EcNode("big", 100), ec_plan.EcNode("small", 2)]
    targets = ec_plan.balanced_ec_distribution(nodes)
    assert targets.count("small") <= 3


def test_plan_ec_encode():
    topo = _topo([
        _node("a:1", volumes=[{"id": 3, "collection": ""}]),
        _node("b:1", rack="r2"),
        _node("c:1", rack="r3"),
    ])
    plan = ec_plan.plan_ec_encode(topo, 3)
    assert plan["source"] == "a:1"
    assert len(plan["moves"]) == 14
    with pytest.raises(LookupError):
        ec_plan.plan_ec_encode(topo, 99)


def test_plan_ec_rebuild():
    # volume 7 has shards 0..11 only (12,13 lost)
    bits = sum(1 << s for s in range(12))
    topo = _topo([
        _node("a:1", ec=[{"id": 7, "ec_index_bits": bits & 0x3F}]),
        _node("b:1", ec=[{"id": 7, "ec_index_bits": bits & ~0x3F}]),
        _node("c:1"),
    ])
    plans = ec_plan.plan_ec_rebuild(topo)
    assert len(plans) == 1
    assert plans[0]["missing"] == [12, 13]
    assert plans[0]["rebuilder"] == "c:1"  # most free slots

    # unrepairable case
    topo2 = _topo([_node("a:1", ec=[{"id": 9, "ec_index_bits": 0b111}])])
    plans2 = ec_plan.plan_ec_rebuild(topo2)
    assert "error" in plans2[0]


def test_plan_ec_balance_drops_duplicates():
    topo = _topo([
        _node("a:1", ec=[{"id": 5, "ec_index_bits": 0b1}]),
        _node("b:1", rack="r2", ec=[{"id": 5, "ec_index_bits": 0b1}]),
    ])
    moves = ec_plan.plan_ec_balance(topo)
    drops = [m for m in moves if m.target == ""]
    assert len(drops) == 1 and drops[0].shard_id == 0


def test_collect_volume_ids():
    topo = _topo([
        _node("a:1", volumes=[{"id": 1, "collection": "", "size": 900},
                              {"id": 2, "collection": "photos", "size": 10}]),
    ])
    assert ec_plan.collect_volume_ids_for_ec_encode(topo) == [1]
    assert ec_plan.collect_volume_ids_for_ec_encode(topo, "photos") == [2]
    assert ec_plan.collect_volume_ids_for_ec_encode(
        topo, "", size_limit=1000, full_percent=50) == [1]


# ---------------- full lifecycle over a live in-process cluster ----------


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(4):
        vs = VolumeServer([str(tmp_path / f"v{i}")], master.url,
                          rack=f"r{i % 2}", data_center="dc1")
        vs.start()
        servers.append(vs)
    deadline = time.time() + 5
    while time.time() < deadline:
        topo = ShellContext(master.url).topology()
        n = sum(len(r["nodes"]) for dc in topo["data_centers"]
                for r in dc["racks"])
        if n == 4:
            break
        time.sleep(0.05)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_full_ec_lifecycle(cluster):
    master, servers = cluster
    mc = MasterClient(master.url, cache_ttl=0.0)
    sh = ShellContext(master.url)
    rng = np.random.default_rng(42)

    # 1. upload files into one volume
    files = {}
    first = operation.upload_data(mc, b"seed")
    vid = int(first.fid.split(",")[0])
    files[first.fid] = b"seed"
    for i in range(25):
        data = rng.integers(0, 256, int(rng.integers(500, 8000)),
                            dtype=np.uint8).tobytes()
        a = mc.assign()
        # force same volume for determinism when possible
        res = operation.upload_to(a["fid"], a["url"], data)
        files[a["fid"]] = data

    # 2. ec.encode every volume
    sh.lock()
    results = sh.ec_encode()
    assert results, "no volumes encoded"
    time.sleep(0.2)

    # EC shards registered on master; volumes gone
    shards = mc.lookup_ec_volume(vid)
    placed_nodes = {loc["url"] for e in shards for loc in e["locations"]}
    assert len(placed_nodes) >= 2, "shards not spread"

    # 3. every file still readable (EC path, remote intervals)
    for fid, data in files.items():
        v = int(fid.split(",")[0])
        urls = [l["url"] for e in mc.lookup_ec_volume(v)
                for l in e["locations"]]
        status = None
        from seaweedfs_tpu.utils.httpd import http_call
        status, body, _ = http_call("GET", f"http://{urls[0]}/{fid}")
        assert status == 200 and body == data, fid

    # 4. kill one server entirely -> rebuild restores full redundancy
    victim = None
    for vs in servers:
        if vs.url in placed_nodes:
            victim = vs
            break
    victim.stop()
    servers.remove(victim)
    # wait for master to prune the dead node
    deadline = time.time() + 40
    while time.time() < deadline:
        mc.invalidate(vid)
        try:
            shards = mc.lookup_ec_volume(vid)
        except Exception:
            time.sleep(0.2)
            continue
        owners = {loc["url"] for e in shards for loc in e["locations"]}
        n_present = sum(1 for e in shards if e["locations"])
        if owners and victim.url not in owners and n_present >= 10:
            break
        master.topo.prune_dead_nodes(timeout=6.0)
        time.sleep(0.3)

    plans = sh.ec_rebuild(apply=True)
    assert plans and "rebuilt" in plans[0], plans
    time.sleep(0.2)
    mc.invalidate(vid)
    shards = mc.lookup_ec_volume(vid)
    present = {e["shard_id"] for e in shards if e["locations"]}
    assert len(present) == layout.TOTAL_SHARDS_COUNT

    for fid, data in files.items():
        v = int(fid.split(",")[0])
        urls = [l["url"] for e in mc.lookup_ec_volume(v)
                for l in e["locations"]]
        from seaweedfs_tpu.utils.httpd import http_call
        status, body, _ = http_call("GET", f"http://{urls[0]}/{fid}")
        assert status == 200 and body == data, f"post-rebuild {fid}"

    # 5. ec.decode back to a normal volume; files readable the plain way
    out = sh.ec_decode(vid)
    assert out["dat_size"] > 0
    time.sleep(0.3)
    mc.invalidate(vid)
    for fid, data in files.items():
        if int(fid.split(",")[0]) != vid:
            continue
        assert operation.read_data(mc, fid) == data, f"post-decode {fid}"
    sh.unlock()
