"""mq broker gRPC plane (reference weed/pb/mq.proto: control plane +
streaming Publish; our Subscribe stream replaces the reference's
separate subscriber client): topic configure/list, streamed publish
acks, replay + live tail, binary values, broker load, shell
mq.topic.list."""

import threading
import time

import pytest

from seaweedfs_tpu.mq.broker import Broker
from seaweedfs_tpu.mq.broker_grpc import MqClient, start_broker_grpc
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture
def mq(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    time.sleep(0.1)
    broker = Broker(fs)
    server, port = start_broker_grpc(broker, port=0)
    client = MqClient(f"127.0.0.1:{port}")
    yield broker, client
    client.close()
    server.stop(grace=None)
    fs.stop()
    vs.stop()
    master.stop()


def test_configure_publish_subscribe(mq):
    broker, client = mq
    assert client.configure_topic("chat", "events", 2) == 2
    # configure is idempotent and keeps the original partition count
    assert client.configure_topic("chat", "events", 8) == 2

    acks = client.publish("chat", "events",
                          [(f"k{i}", f"v{i}".encode()) for i in range(20)])
    assert len(acks) == 20
    assert acks == sorted(acks) and len(set(acks)) == 20  # monotonic

    records = list(client.subscribe("chat", "events"))
    assert len(records) == 20
    assert sorted(r["value"] for r in records) == sorted(
        f"v{i}".encode() for i in range(20))
    # same key lands on the same partition
    parts = {r["key"]: r["partition"] for r in records}
    acks2 = client.publish("chat", "events", [("k3", b"again")])
    assert len(acks2) == 1
    again = [r for r in client.subscribe("chat", "events")
             if r["value"] == b"again"]
    assert again[0]["partition"] == parts["k3"]

    load = client.broker_load()
    assert load["message_count"] == 21
    assert load["bytes_count"] > 21 * 30

    topics = client.list_topics()
    assert topics == [
        {"namespace": "chat", "topic": "events", "partition_count": 2}]


def test_empty_record_first_in_stream_is_published(mq):
    # regression: the init frame carries no record, so an empty-key/
    # empty-value record as the FIRST item must not be swallowed
    broker, client = mq
    client.configure_topic("e", "t", 1)
    acks = client.publish("e", "t", [("", b"")])
    assert len(acks) == 1
    [rec] = list(client.subscribe("e", "t"))
    assert rec["key"] == "" and rec["value"] == b""


def test_publish_unknown_topic_errors(mq):
    broker, client = mq
    with pytest.raises(RuntimeError, match="not found"):
        client.publish("nope", "missing", [("k", b"v")])


def test_binary_values_roundtrip(mq):
    broker, client = mq
    client.configure_topic("bin", "blobs", 1)
    payload = bytes(range(256))
    client.publish("bin", "blobs", [("k", payload)])
    broker.flush()  # force the JSONL segment path, not just the live ring
    [rec] = list(client.subscribe("bin", "blobs"))
    assert rec["value"] == payload


def test_segment_overflow_autoflush(mq, monkeypatch):
    # crossing SEGMENT_MAX_BYTES pops the segment and uploads it
    # outside the broker lock (two-phase flush); a subscriber attaching
    # mid-stream still sees every record exactly once, and the >2KB
    # segment takes the chunked-upload branch
    import seaweedfs_tpu.mq.broker as broker_mod
    broker, client = mq
    monkeypatch.setattr(broker_mod, "SEGMENT_MAX_BYTES", 8 * 1024)
    client.configure_topic("big", "stream", 1)
    payload = b"x" * 1024
    acks = client.publish("big", "stream",
                          [(f"k{i}", payload) for i in range(40)])
    assert len(acks) == 40
    # at least one segment was flushed to the filer
    segs = broker.filer.list_entries("/topics/big/stream/p00", limit=100)
    assert len(segs) >= 2
    recs = list(client.subscribe("big", "stream"))
    assert len(recs) == 40
    assert sorted(r["key"] for r in recs) == sorted(
        f"k{i}" for i in range(40))
    assert all(r["value"] == payload for r in recs)


def test_live_tail_sees_replay_then_new_records(mq):
    broker, client = mq
    client.configure_topic("t", "tail", 1)
    client.publish("t", "tail", [("a", b"old1"), ("a", b"old2")])
    broker.flush()
    client.publish("t", "tail", [("a", b"old3")])  # unflushed in-memory

    got, done = [], threading.Event()

    def consume():
        for rec in client.subscribe("t", "tail", tail=True, timeout=30):
            got.append(rec)
            if len(got) == 5:
                break
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.time() + 10
    while len(got) < 3 and time.time() < deadline:
        time.sleep(0.05)
    assert [r["value"] for r in got] == [b"old1", b"old2", b"old3"]
    assert all(r["seq"] == 0 for r in got)  # replayed
    client.publish("t", "tail", [("a", b"new1"), ("a", b"new2")])
    assert done.wait(10), f"tail delivered only {len(got)} records"
    assert [r["value"] for r in got[3:]] == [b"new1", b"new2"]
    assert all(r["seq"] > 0 for r in got[3:])  # live


def test_flush_names_assigned_at_pop_order(mq):
    # segment filenames are assigned under the lock at pop time, so
    # replay order (filename sort) matches record order even if the
    # slower upload completes last
    broker, client = mq
    client.configure_topic("o", "t", 1)
    broker.publish("o", "t", "k", "first")
    a = broker._begin_flush("o/t", 0)
    broker.publish("o", "t", "k", "second")
    b = broker._begin_flush("o/t", 0)
    assert a[0] < b[0]
    # complete them OUT of order; replay must still be first, second
    broker._complete_flush("o", "t", 0, *b)
    broker._complete_flush("o", "t", 0, *a)
    vals = [r["value"] for r in broker.subscribe("o", "t")]
    assert vals == ["first", "second"]


def test_tail_overflow_raises_not_skips(mq):
    import collections
    from seaweedfs_tpu.mq.broker import MqTailOverflow
    broker, client = mq
    client.configure_topic("lag", "t", 1)
    broker._recent = collections.deque(broker._recent, maxlen=8)
    gen = broker.subscribe("lag", "t", tail=True)
    broker.publish("lag", "t", "k", "v0")
    assert next(gen)["value"] == "v0"  # attach: replay, last=1
    for _ in range(12):  # seqs 2..13; maxlen-8 ring evicts 2..5 unseen
        broker.publish("lag", "t", "k", "v")
    with pytest.raises(MqTailOverflow):
        next(gen)


def test_tail_survives_foreign_topic_churn(mq):
    # Ring eviction is tracked per (topic, partition): a busy foreign
    # topic churning the shared ring must NOT abort a quiet topic's
    # tail when none of the evicted records matched its subscription.
    import collections
    broker, client = mq
    client.configure_topic("quiet", "t", 1)
    client.configure_topic("busy", "t", 1)
    broker._recent = collections.deque(broker._recent, maxlen=8)
    gen = broker.subscribe("quiet", "t", tail=True)
    broker.publish("quiet", "t", "k", "q0")
    assert next(gen)["value"] == "q0"
    for _ in range(20):  # evicts well past the quiet tailer's cursor
        broker.publish("busy", "t", "k", "noise")
    broker.publish("quiet", "t", "k", "q1")
    assert next(gen)["value"] == "q1"  # no MqTailOverflow


def test_shell_mq_topic_list(mq, tmp_path):
    broker, client = mq
    client.configure_topic("ns1", "orders", 4)
    from seaweedfs_tpu.shell.commands import ShellContext
    from seaweedfs_tpu.shell.repl import run_command
    sh = ShellContext(broker.fs.master_url)
    out = run_command(sh, "mq.topic.list")
    assert {"namespace": "ns1", "topic": "orders",
            "partition_count": 4} in out["topics"]
