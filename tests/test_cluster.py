"""In-process cluster integration: master + volume servers over real HTTP.

The reference's equivalent is the out-of-process `weed server` harness
(test/s3/basic); we run everything in threads on loopback sockets."""

import time

import numpy as np
import pytest

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call, http_json


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(3):
        vs = VolumeServer([str(tmp_path / f"v{i}")], master.url,
                          rack=f"r{i % 2}", data_center="dc1")
        vs.start()
        servers.append(vs)
    # wait for registration
    deadline = time.time() + 5
    while time.time() < deadline:
        topo = http_json("GET", f"http://{master.url}/dir/status")
        nodes = [n for dc in topo["Topology"]["data_centers"]
                 for r in dc["racks"] for n in r["nodes"]]
        if len(nodes) == 3:
            break
        time.sleep(0.05)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_assign_upload_read_delete(cluster):
    master, servers = cluster
    mc = MasterClient(master.url)
    data = b"hello seaweedfs-tpu" * 100
    res = operation.upload_data(mc, data, name="greeting.txt")
    assert res.fid

    got = operation.read_data(mc, res.fid)
    assert got == data

    assert operation.delete_file(mc, res.fid)
    with pytest.raises(Exception):
        operation.read_data(mc, res.fid)


def test_replicated_write_lands_on_two_servers(cluster):
    master, servers = cluster
    mc = MasterClient(master.url)
    a = mc.assign(replication="001")
    assert a.get("replicas"), a
    data = b"replicated payload"
    operation.upload_to(a["fid"], a["url"], data)
    time.sleep(0.1)
    vid = int(a["fid"].split(",")[0])
    locs = mc.lookup_volume(vid)
    assert len(locs) == 2
    # read directly from each replica
    for loc in locs:
        status, body, _ = http_call("GET", f"http://{loc['url']}/{a['fid']}")
        assert status == 200 and body == data


def test_many_files_roundtrip(cluster):
    master, servers = cluster
    mc = MasterClient(master.url)
    rng = np.random.default_rng(0)
    files = {}
    for i in range(30):
        data = rng.integers(0, 256, int(rng.integers(100, 3000)),
                            dtype=np.uint8).tobytes()
        res = operation.upload_data(mc, data, name=f"f{i}")
        files[res.fid] = data
    for fid, data in files.items():
        assert operation.read_data(mc, fid) == data


def test_grow_and_cluster_status(cluster):
    master, servers = cluster
    out = http_json("POST", f"http://{master.url}/vol/grow?count=2")
    assert out["count"] == 2
    st = http_json("GET", f"http://{master.url}/cluster/status")
    assert st["IsLeader"] and st["MaxVolumeId"] >= 2
