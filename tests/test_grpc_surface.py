"""Round-4 gRPC surface completion (verdict gap #5/#7):
- filer AssignVolume/LookupVolume/Statistics — the pure-gRPC write path
  (reference weed/pb/filer.proto:36)
- volume VolumeTailSender/Receiver + VolumeIncrementalCopy — replica
  catch-up (reference weed/pb/volume_server.proto:31,64)
- ReadVolumeFileStatus / VolumeNeedleStatus / Ping / Query
- renamed proto packages (weedtpu_*) so a real SeaweedFS client can
  never silently mis-decode our messages (round-3 ADVICE)."""

import json
import time

import pytest

from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.pb import master_pb2 as mpb
from seaweedfs_tpu.pb import volume_server_pb2 as vpb
from seaweedfs_tpu.server.filer_grpc import GrpcFilerClient
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_grpc import GrpcVolumeClient
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call, http_json


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url, grpc_port=0)
    vs.start()
    fs = FilerServer(master.url, grpc_port=0)
    fs.start()
    time.sleep(0.1)
    fclient = GrpcFilerClient(f"127.0.0.1:{fs.grpc_port}")
    vclient = GrpcVolumeClient(f"127.0.0.1:{vs.grpc_port}")
    yield master, vs, fs, fclient, vclient
    fclient.close()
    vclient.close()
    fs.stop()
    vs.stop()
    master.stop()


def test_proto_packages_renamed():
    assert fpb.DESCRIPTOR.package == "weedtpu_filer_pb"
    assert mpb.DESCRIPTOR.package == "weedtpu_master_pb"
    assert vpb.DESCRIPTOR.package == "weedtpu_volume_server_pb"


def test_pure_grpc_write_path(stack):
    """A client that speaks ONLY gRPC for metadata: AssignVolume ->
    HTTP data POST (like the reference) -> CreateEntry -> read back via
    LookupDirectoryEntry + LookupVolume."""
    master, vs, fs, fc, vc = stack
    a = fc.assign_volume(count=1, path="/docs/hello.txt")
    assert a.file_id and a.url
    payload = b"written through the grpc metadata plane"
    status, _, _ = http_call("POST", f"http://{a.url}/{a.file_id}",
                             body=payload)
    assert status == 201

    entry = fpb.Entry(name="hello.txt")
    entry.chunks.append(fpb.FileChunk(
        file_id=a.file_id, offset=0, size=len(payload),
        mtime=time.time_ns()))
    entry.attributes.file_size = len(payload)
    entry.attributes.file_mode = 0o644
    entry.attributes.mtime = int(time.time())
    fc.create_entry("/docs", entry)

    got = fc.lookup("/docs", "hello.txt")
    assert got.name == "hello.txt"
    assert got.chunks[0].file_id == a.file_id

    # volume lookup over gRPC resolves the chunk's location
    vid = a.file_id.split(",")[0]
    locs = fc.lookup_volume([vid])
    assert vid in locs and locs[vid]
    status, body, _ = http_call(
        "GET", f"http://{locs[vid][0]}/{a.file_id}")
    assert status == 200 and body == payload

    # and the filer HTTP read path agrees end-to-end
    status, body, _ = http_call("GET", f"http://{fs.url}/docs/hello.txt")
    assert status == 200 and body == payload


def test_filer_statistics_and_configuration(stack):
    master, vs, fs, fc, vc = stack
    # upload something so used_size > 0
    a = fc.assign_volume()
    http_call("POST", f"http://{a.url}/{a.file_id}", body=b"x" * 4096)
    vs.heartbeat_once()
    st = fc.statistics()
    assert st.total_size > 0
    conf = fc.get_configuration()
    assert list(conf.masters) == [master.url]


def _put(master, data, fid=None):
    a = http_json("GET", f"http://{master.url}/dir/assign")
    status, _, _ = http_call("POST", f"http://{a['url']}/{a['fid']}",
                             body=data)
    assert status == 201
    return a["fid"]


def test_volume_file_and_needle_status(stack):
    master, vs, fs, fc, vc = stack
    fid = _put(master, b"status-check-payload")
    vid = int(fid.split(",")[0])
    st = vc.read_volume_file_status(vid)
    assert st.volume_id == vid
    assert st.file_count == 1
    assert st.dat_file_size > 0 and st.idx_file_size > 0
    assert st.last_append_at_ns > 0

    key = int(fid.split(",")[1][:-8], 16)
    ns = vc.volume_needle_status(vid, key)
    assert ns.needle_id == key and ns.size == len(b"status-check-payload")

    with pytest.raises(Exception):
        vc.volume_needle_status(vid, 0xDEAD)


def test_ping(stack):
    master, vs, fs, fc, vc = stack
    p = vc.ping()
    assert p.stop_time_ns >= p.start_time_ns
    p2 = vc.ping(target=master.url, target_type="master")
    assert p2.remote_time_ns >= p2.start_time_ns


def test_tail_sender_and_incremental_copy(stack):
    master, vs, fs, fc, vc = stack
    t0 = time.time_ns()
    fids = [_put(master, f"tail-{i}".encode() * 10) for i in range(5)]
    vid = int(fids[0].split(",")[0])

    needles = list(vc.volume_tail_needles(vid, since_ns=0))
    assert len(needles) == 5
    assert all(n.append_at_ns > t0 for n in needles)
    datas = {bytes(n.data) for n in needles}
    assert b"tail-0" * 10 in datas and b"tail-4" * 10 in datas

    # since cursor: nothing new after the last append
    last = max(n.append_at_ns for n in needles)
    assert list(vc.volume_tail_needles(vid, since_ns=last)) == []

    # incremental copy streams raw record bytes
    raw = vc.volume_incremental_copy(vid, since_ns=0)
    assert len(raw) > sum(len(f"tail-{i}".encode() * 10)
                          for i in range(5))
    assert raw == vc.volume_incremental_copy(vid, since_ns=0)


def test_replica_catch_up_via_tail_receiver(stack, tmp_path):
    """The verdict's 'done' bar: a (restarted/lagging) replica catches
    up from its peer via VolumeTailReceiver."""
    master, vs, fs, fc, vc = stack
    # source data on vs
    fids = [_put(master, f"replica-{i}".encode()) for i in range(3)]
    vid = int(fids[0].split(",")[0])
    v_src = vs.store.find_volume(vid)

    # a second volume server with an EMPTY copy of the volume (the
    # lagging replica that just restarted)
    vs2 = VolumeServer([str(tmp_path / "v2")], master.url, grpc_port=0)
    vs2.start()
    try:
        vs2.store.add_volume(vid, v_src.collection)
        vc2 = GrpcVolumeClient(f"127.0.0.1:{vs2.grpc_port}")
        try:
            vc2.volume_tail_receiver(vid, since_ns=0,
                                     source=f"127.0.0.1:{vs.grpc_port}")
        finally:
            vc2.close()
        v_dst = vs2.store.find_volume(vid)
        assert v_dst.file_count() == 3
        for fid in fids:
            key = int(fid.split(",")[1][:-8], 16)
            n = v_dst.read_needle(key)
            assert bytes(n.data) == \
                bytes(v_src.read_needle(key).data)
        # deletes replicate too
        key0 = int(fids[0].split(",")[1][:-8], 16)
        cursor = v_dst.last_append_at_ns
        v_src.delete_needle(key0)
        vc2b = GrpcVolumeClient(f"127.0.0.1:{vs2.grpc_port}")
        try:
            vc2b.volume_tail_receiver(vid, since_ns=cursor,
                                      source=f"127.0.0.1:{vs.grpc_port}")
        finally:
            vc2b.close()
        assert not v_dst.has_needle(key0)
    finally:
        vs2.stop()


def test_filer_misc_rpcs(stack):
    """AppendToEntry / CollectionList / DeleteCollection / Ping /
    SubscribeLocalMetadata (reference filer.proto parity)."""
    master, vs, fs, fc, vc = stack

    # AppendToEntry builds a log-style file chunk by chunk
    pieces = []
    for i in range(3):
        a = fc.assign_volume()
        blob = f"segment-{i}|".encode()
        http_call("POST", f"http://{a.url}/{a.file_id}", body=blob)
        pieces.append((a.file_id, blob))
    for fid, blob in pieces:
        fc.append_to_entry("/logs", "app.log",
                           [fpb.FileChunk(file_id=fid, size=len(blob),
                                          mtime=time.time_ns())])
    status, body, _ = http_call("GET", f"http://{fs.url}/logs/app.log")
    assert status == 200
    assert body == b"segment-0|segment-1|segment-2|"

    # appending to an INLINE-content entry spills the content to a
    # chunk first (round-4 review: content+chunks coexisting makes the
    # appended bytes unreadable)
    http_call("POST", f"http://{fs.url}/logs/tiny.log", body=b"head|")
    a = fc.assign_volume()
    http_call("POST", f"http://{a.url}/{a.file_id}", body=b"tail")
    r = fc._unary("AppendToEntry", fpb.AppendToEntryRequest(
        directory="/logs", entry_name="tiny.log",
        chunks=[fpb.FileChunk(file_id=a.file_id, size=4,
                              mtime=time.time_ns())]),
        fpb.AppendToEntryResponse)
    assert not r.error
    status, body, _ = http_call("GET", f"http://{fs.url}/logs/tiny.log")
    assert status == 200 and body == b"head|tail"

    # collections appear/disappear via gRPC
    a = fc.assign_volume(collection="grpccol")
    http_call("POST", f"http://{a.url}/{a.file_id}", body=b"c")
    vs.heartbeat_once()
    assert "grpccol" in fc.collection_list()
    fc.delete_collection("grpccol")
    vs.heartbeat_once()
    assert "grpccol" not in fc.collection_list()

    # ping self and via target
    p = fc.ping()
    assert p.stop_time_ns >= p.start_time_ns

    # SubscribeLocalMetadata streams the same log
    ch = fc.channel.unary_stream(
        "/weedtpu_filer_pb.SeaweedFiler/SubscribeLocalMetadata",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=fpb.SubscribeMetadataResponse.FromString)
    call = ch(fpb.SubscribeMetadataRequest(client_name="t",
                                           path_prefix="/logs",
                                           since_ns=0))
    first = next(iter(call))
    assert first.directory.startswith("/logs")
    call.cancel()


def test_master_admin_rpcs(tmp_path):
    """Statistics / CollectionList / CollectionDelete /
    GetMasterConfiguration on the master gRPC plane (reference
    master.proto parity)."""
    from seaweedfs_tpu.server.master_grpc import GrpcMasterClient
    master = MasterServer(volume_size_limit_mb=64, grpc_port=0)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    time.sleep(0.1)
    client = GrpcMasterClient(f"127.0.0.1:{master.grpc_port}")
    try:
        a = http_json("GET", f"http://{master.url}/dir/assign"
                             "?collection=mcol")
        http_call("POST", f"http://{a['url']}/{a['fid']}", body=b"zz")
        vs.heartbeat_once()

        st = client._call("Statistics", mpb.StatisticsRequest(),
                          mpb.StatisticsResponse)
        assert st.total_size > 0 and st.used_size > 0

        cl = client._call("CollectionList", mpb.CollectionListRequest(),
                          mpb.CollectionListResponse)
        assert any(c.name == "mcol" for c in cl.collections)

        client._call("CollectionDelete",
                     mpb.CollectionDeleteRequest(name="mcol"),
                     mpb.CollectionDeleteResponse)
        vs.heartbeat_once()
        cl = client._call("CollectionList", mpb.CollectionListRequest(),
                          mpb.CollectionListResponse)
        assert not any(c.name == "mcol" for c in cl.collections)

        conf = client._call("GetMasterConfiguration",
                            mpb.GetMasterConfigurationRequest(),
                            mpb.GetMasterConfigurationResponse)
        assert conf.volume_size_limit_m_b == 64
        assert conf.leader
    finally:
        client.close()
        vs.stop()
        master.stop()


def test_query_rpc(stack):
    master, vs, fs, fc, vc = stack
    rows = [{"name": "ada", "age": 36}, {"name": "grace", "age": 45},
            {"name": "alan", "age": 41}]
    payload = "\n".join(json.dumps(r) for r in rows).encode()
    fid = _put(master, payload)
    out = vc.query([fid], selections=["name"],
                   filter_field="age", filter_op=">", filter_value="40")
    got = [json.loads(l) for l in out.decode().splitlines() if l]
    assert sorted(g["name"] for g in got) == ["alan", "grace"]
