"""Zero-copy read plane: sendfile GETs, volume-direct redirects, and
the fallback ladder.

Every test here is a comparator at one of the plane's seams: the
sendfile path must be BIT-IDENTICAL to the buffered path it replaces
(`vs.zero_copy = False`), and the volume-direct redirect must be
bit-identical to the filer/S3 proxy it bypasses (`?proxy=1`,
`volume_redirect = False`).  The X-Weed-Zero-Copy response header is
the witness for WHICH path served — asserting its presence/absence is
how the fallback-ladder tests prove cached and EC-degraded reads
stayed buffered."""

import hashlib
import os
import socket
import time

import numpy as np
import pytest

from seaweedfs_tpu.gateway.s3_server import S3Server
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils import headers as weed_headers
from seaweedfs_tpu.utils.httpd import (FileSlice, HttpServer, Response,
                                       http_call, http_json, send_file)

ZC = weed_headers.ZERO_COPY


def _hdr(headers, name, default=None):
    return next((v for k, v in headers.items() if k.lower() == name.lower()),
                default)


def _payload(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, n, dtype=np.uint8).tobytes()


# --------------------------------------------------- volume server


@pytest.fixture
def vstack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    time.sleep(0.1)
    yield master, vs
    vs.stop()
    master.stop()


def _upload(master, data):
    a = http_json("GET", f"http://{master.url}/dir/assign")
    status, _, _ = http_call("POST", f"http://{a['url']}/{a['fid']}",
                             body=data)
    assert status < 300
    return a["url"], a["fid"]


def test_sendfile_vs_buffered_bit_identity(vstack):
    """Whole-needle GET: same status, body, and ETag on both paths —
    and the header witnesses which path actually ran."""
    master, vs = vstack
    data = _payload(1 << 20)
    url, fid = _upload(master, data)

    status, body, h = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data
    assert _hdr(h, ZC) == "1", "1MB needle should take the sendfile path"
    etag_zc = _hdr(h, "ETag")

    vs.zero_copy = False
    status, body2, h2 = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body2 == data
    assert _hdr(h2, ZC) is None
    assert _hdr(h2, "ETag") == etag_zc


@pytest.mark.parametrize("spec,lo,hi", [
    ("bytes=0-65535", 0, 65535),              # aligned head window
    ("bytes=100000-299999", 100000, 299999),  # interior window
    ("bytes=0-0", 0, 0),                      # single byte
    ("bytes=-1234", (1 << 20) - 1234, (1 << 20) - 1),   # suffix form
    ("bytes=1048570-", 1048570, (1 << 20) - 1),          # open-ended tail
    ("bytes=-9999999", 0, (1 << 20) - 1),     # over-long suffix clamps
])
def test_range_bit_identity(vstack, spec, lo, hi):
    master, vs = vstack
    data = _payload(1 << 20, seed=1)
    url, fid = _upload(master, data)

    status, body, h = http_call("GET", f"http://{url}/{fid}",
                                headers={"Range": spec})
    assert status == 206 and body == data[lo:hi + 1]
    assert _hdr(h, ZC) == "1"
    assert _hdr(h, "Content-Range") == f"bytes {lo}-{hi}/{len(data)}"

    vs.zero_copy = False
    status, body2, h2 = http_call("GET", f"http://{url}/{fid}",
                                  headers={"Range": spec})
    assert status == 206 and body2 == body
    assert _hdr(h2, ZC) is None
    assert _hdr(h2, "Content-Range") == _hdr(h, "Content-Range")


def test_range_unsatisfiable_416_both_paths(vstack):
    master, vs = vstack
    data = _payload(1 << 20, seed=2)
    url, fid = _upload(master, data)
    for zero_copy in (True, False):
        vs.zero_copy = zero_copy
        status, _, h = http_call("GET", f"http://{url}/{fid}",
                                 headers={"Range": "bytes=9999999-"})
        assert status == 416, f"zero_copy={zero_copy}"
        assert _hdr(h, "Content-Range") == f"bytes */{len(data)}"


def test_malformed_range_serves_whole_body_both_paths(vstack):
    # RFC 7233: an unparseable Range header is ignored, not an error
    master, vs = vstack
    data = _payload(256 * 1024, seed=3)
    url, fid = _upload(master, data)
    for zero_copy in (True, False):
        vs.zero_copy = zero_copy
        status, body, _ = http_call("GET", f"http://{url}/{fid}",
                                    headers={"Range": "bytes=x-y"})
        assert status == 200 and body == data


def test_threshold_keeps_small_needles_buffered(vstack):
    """Payloads under zero_copy_min stay on the buffered path (they
    feed the needle cache); dropping the threshold flips the SAME
    needle to sendfile with an identical body."""
    master, vs = vstack
    data = _payload(4096, seed=4)
    url, fid = _upload(master, data)

    status, body, h = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data and _hdr(h, ZC) is None

    vs.zero_copy_min = 0
    if vs.store.needle_cache is not None:
        vs.store.needle_cache.invalidate_volume(int(fid.split(",")[0]))
    status, body2, h2 = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body2 == data
    assert _hdr(h2, ZC) == "1"


def test_fallback_ladder_cached_read(vstack):
    """A needle admitted to the record cache is served from memory —
    the descriptor path must defer to it (no ZC header), and the body
    must stay bit-identical."""
    master, vs = vstack
    data = _payload(128 * 1024, seed=5)
    url, fid = _upload(master, data)

    vs.zero_copy = False           # buffered read admits to the cache
    status, body, _ = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data

    vs.zero_copy = True
    status, body2, h2 = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body2 == data
    assert _hdr(h2, ZC) is None, \
        "cache hit must win over the descriptor path"


def test_fallback_ladder_ec_degraded(vstack, tmp_path):
    """After EC conversion (and shard loss) the read survives via the
    reconstruction path — buffered, never sendfile."""
    from seaweedfs_tpu.storage.erasure_coding import layout

    master, vs = vstack
    data = _payload(96 * 1024, seed=6)
    url, fid = _upload(master, data)
    vid = int(fid.split(",")[0])

    base = vs.store.generate_ec_shards(vid)
    vs.store.delete_volume(vid)
    vs.store.mount_ec_shards("", vid, list(range(14)))

    status, body, h = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data
    assert _hdr(h, ZC) is None, "EC reads have no contiguous fd window"

    # degrade: drop 4 shards entirely -> k-column reconstruction
    victims = [0, 3, 7, 11]
    vs.store.unmount_ec_shards(vid, victims)
    for sid in victims:
        os.remove(base + layout.shard_ext(sid))
    status, body, h = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data
    assert _hdr(h, ZC) is None


def test_mid_transfer_disconnect_leaves_server_healthy(vstack):
    """A client that vanishes mid-sendfile must cost exactly its own
    connection: the next requests on fresh connections still serve the
    full, correct body."""
    master, vs = vstack
    data = _payload(4 << 20, seed=7)
    url, fid = _upload(master, data)
    host, port = url.split(":")

    for _ in range(3):
        sock = socket.create_connection((host, int(port)), timeout=5)
        sock.sendall(f"GET /{fid} HTTP/1.1\r\nHost: x\r\n\r\n"
                     .encode())
        sock.recv(65536)           # headers + first payload bytes
        sock.close()               # vanish mid-body

    status, body, h = http_call("GET", f"http://{url}/{fid}")
    assert status == 200 and body == data
    assert _hdr(h, ZC) == "1"


# ------------------------------------------- transport edge windows


def test_send_file_primitive_edges(tmp_path):
    """send_file at the transport layer: 0-byte windows, windows that
    end exactly at EOF, and interior windows all frame correctly on a
    keep-alive connection (a framing bug would corrupt request 2)."""
    blob = _payload(100_000, seed=8)
    p = tmp_path / "w.dat"
    p.write_bytes(blob)
    fd = os.open(p, os.O_RDONLY)

    srv = HttpServer()

    def serve(req):
        off = int(req.query.get("off", "0"))
        cnt = int(req.query.get("cnt", "0"))
        return send_file(fd, off, cnt)

    srv.add("GET", "/w", serve)
    srv.start()
    try:
        base = f"http://{srv.host}:{srv.port}/w"
        windows = [(0, 0), (0, 100_000), (99_999, 1), (50_000, 0),
                   (12_345, 67_890), (100_000, 0)]
        for off, cnt in windows:
            status, body, _ = http_call("GET",
                                        f"{base}?off={off}&cnt={cnt}")
            assert status == 200, (off, cnt)
            assert body == blob[off:off + cnt], (off, cnt)
    finally:
        srv.stop()
        os.close(fd)


def test_file_slice_owns_its_fd():
    r, w = os.pipe()
    os.close(w)
    fs = FileSlice(r, 0, 0)
    assert len(fs) == 0
    fs.close()
    fs.close()                     # idempotent
    with pytest.raises(OSError):
        os.fstat(r)                # really closed


def test_response_keeps_memoryview_uncopied():
    blob = bytearray(b"x" * 64)
    mv = memoryview(blob)[8:16]
    resp = Response(mv)
    assert resp.body is mv         # no bytes() rematerialization


# ------------------------------------------------ filer redirects


@pytest.fixture
def fstack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    time.sleep(0.2)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def test_filer_redirects_single_chunk_gets(fstack):
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    data = _payload(3 << 20, seed=9)         # 1 chunk (< 4MB)
    status, _, _ = http_call("POST", f"{base}/d/one.bin", body=data)
    assert status == 201

    # raw 302: Location points at a volume server, NOTHING is proxied
    status, body, h = http_call("GET", f"{base}/d/one.bin",
                                follow_redirects=False)
    assert status == 302 and body == b""
    loc = _hdr(h, "Location")
    assert loc and vs.url in loc

    # followed redirect == proxied comparator, bit for bit
    status, direct, h = http_call("GET", f"{base}/d/one.bin")
    assert status == 200 and direct == data
    assert _hdr(h, ZC) == "1", "volume-direct GET should sendfile"
    status, proxied, h = http_call("GET", f"{base}/d/one.bin?proxy=1")
    assert status == 200 and proxied == data
    assert _hdr(h, ZC) is None


def test_filer_redirect_honors_range(fstack):
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    data = _payload(3 << 20, seed=10)
    http_call("POST", f"{base}/d/r.bin", body=data)

    for spec, lo, hi in [("bytes=100-999", 100, 999),
                        ("bytes=-4096", len(data) - 4096, len(data) - 1),
                        ("bytes=3145000-", 3145000, len(data) - 1)]:
        status, body, h = http_call("GET", f"{base}/d/r.bin",
                                    headers={"Range": spec})
        assert status == 206 and body == data[lo:hi + 1], spec
        status, body2, h2 = http_call("GET", f"{base}/d/r.bin?proxy=1",
                                      headers={"Range": spec})
        assert status == 206 and body2 == body, spec
        assert _hdr(h2, "Content-Range") == _hdr(h, "Content-Range")


def test_filer_proxy_range_conformance(fstack):
    """The proxied (multi-chunk) path assembles ranges across chunk
    boundaries and 416s with the total length."""
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    data = _payload(9_000_000, seed=11)      # 3 chunks
    http_call("POST", f"{base}/d/big.bin", body=data)

    # multi-chunk entries are NOT redirect-eligible
    status, _, _ = http_call("GET", f"{base}/d/big.bin",
                             follow_redirects=False)
    assert status == 200

    lo, hi = 4_000_000, 8_500_000            # spans all 3 chunks
    status, body, h = http_call(
        "GET", f"{base}/d/big.bin",
        headers={"Range": f"bytes={lo}-{hi}"})
    assert status == 206 and body == data[lo:hi + 1]
    assert _hdr(h, "Content-Range") == f"bytes {lo}-{hi}/{len(data)}"

    status, _, h = http_call("GET", f"{base}/d/big.bin",
                             headers={"Range": "bytes=99999999-"})
    assert status == 416
    assert _hdr(h, "Content-Range") == f"bytes */{len(data)}"


def test_filer_redirect_disabled_comparator(fstack):
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    data = _payload(2 << 20, seed=12)
    http_call("POST", f"{base}/d/c.bin", body=data)

    fs.volume_redirect = False
    status, body, _ = http_call("GET", f"{base}/d/c.bin",
                                follow_redirects=False)
    assert status == 200 and body == data    # proxied, no 302


def test_inline_entries_never_redirect(fstack):
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    http_call("POST", f"{base}/d/tiny.txt", body=b"inline me")
    status, body, _ = http_call("GET", f"{base}/d/tiny.txt",
                                follow_redirects=False)
    assert status == 200 and body == b"inline me"


def test_small_files_stay_proxied(fstack):
    """Single-chunk entries under volume_redirect_min keep the proxy
    path: the filer's reader cache and deadline-bounded fetches own
    the hot small tail; only bulk reads skip the hop."""
    master, vs, fs = fstack
    base = f"http://{fs.url}"
    data = _payload(64 * 1024, seed=16)      # chunked, but small
    http_call("POST", f"{base}/d/small.bin", body=data)
    status, body, _ = http_call("GET", f"{base}/d/small.bin",
                                follow_redirects=False)
    assert status == 200 and body == data    # proxied, no 302

    fs.volume_redirect_min = 0
    status, body, _ = http_call("GET", f"{base}/d/small.bin",
                                follow_redirects=False)
    assert status == 302 and body == b""


def test_jwt_stamped_on_volume_direct_urls(tmp_path):
    """With jwt.signing.read in force the 302 Location must carry a
    fid-scoped token — and the volume server must reject the same URL
    with the token stripped."""
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url,
                      jwt_read_key="read-secret")
    vs.start()
    fs = FilerServer(master.url)
    fs._jwt_read_key = "read-secret"         # same shared key
    fs.start()
    time.sleep(0.2)
    try:
        base = f"http://{fs.url}"
        data = _payload(1 << 20, seed=13)
        status, _, _ = http_call("POST", f"{base}/d/s.bin", body=data)
        assert status == 201

        status, _, h = http_call("GET", f"{base}/d/s.bin",
                                 follow_redirects=False)
        assert status == 302
        loc = _hdr(h, "Location")
        assert "?jwt=" in loc

        status, body, _ = http_call("GET", loc)
        assert status == 200 and body == data

        stripped = loc.split("?jwt=")[0]
        status, _, _ = http_call("GET", stripped)
        assert status == 401

        # end-to-end with auto-follow
        status, body, _ = http_call("GET", f"{base}/d/s.bin")
        assert status == 200 and body == data
    finally:
        fs.stop()
        vs.stop()
        master.stop()


# --------------------------------------------------- S3 gateway


@pytest.fixture
def s3stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    fs = FilerServer(master.url)
    fs.start()
    s3 = S3Server(fs)
    s3.start()
    time.sleep(0.2)
    yield vs, fs, s3
    s3.stop()
    fs.stop()
    vs.stop()
    master.stop()


def test_s3_redirect_vs_proxy_bit_identity(s3stack):
    vs, fs, s3 = s3stack
    base = f"http://{s3.url}"
    http_call("PUT", f"{base}/zc")
    data = _payload(3 << 20, seed=14)
    status, _, _ = http_call("PUT", f"{base}/zc/obj.bin", body=data)
    assert status == 200

    status, body, h = http_call("GET", f"{base}/zc/obj.bin",
                                follow_redirects=False)
    assert status == 302 and body == b""
    assert vs.url in _hdr(h, "Location")

    status, direct, _ = http_call("GET", f"{base}/zc/obj.bin")
    assert status == 200 and direct == data
    status, proxied, _ = http_call("GET", f"{base}/zc/obj.bin?proxy=1")
    assert status == 200 and proxied == data
    assert hashlib.sha256(direct).digest() == \
        hashlib.sha256(proxied).digest()

    # S3-side kill switch falls back to proxying without a client change
    s3.volume_redirect = False
    status, body, _ = http_call("GET", f"{base}/zc/obj.bin",
                                follow_redirects=False)
    assert status == 200 and body == data
    s3.volume_redirect = True


def test_s3_range_conformance(s3stack):
    vs, fs, s3 = s3stack
    base = f"http://{s3.url}"
    http_call("PUT", f"{base}/rg")
    data = _payload(3 << 20, seed=15)
    http_call("PUT", f"{base}/rg/o.bin", body=data)

    for spec, lo, hi in [("bytes=0-1023", 0, 1023),
                        ("bytes=-512", len(data) - 512, len(data) - 1)]:
        status, body, h = http_call("GET", f"{base}/rg/o.bin",
                                    headers={"Range": spec})
        assert status == 206 and body == data[lo:hi + 1]
        status, body2, _ = http_call("GET", f"{base}/rg/o.bin?proxy=1",
                                     headers={"Range": spec})
        assert status == 206 and body2 == body

    status, _, h = http_call("GET", f"{base}/rg/o.bin",
                             headers={"Range": "bytes=99999999-"})
    assert status == 416
    assert _hdr(h, "Content-Range") == f"bytes */{len(data)}"
