"""End-to-end store test: volume -> ec.encode -> serve -> degrade -> rebuild.

This is the 'minimum end-to-end slice' of SURVEY.md §7: write files into a
volume, EC-encode it through the coder, kill shards, and verify every byte
survives via degraded reads and rebuild."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import make_coder
from seaweedfs_tpu.storage.erasure_coding import encoder as ecenc
from seaweedfs_tpu.storage.erasure_coding import layout
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import DeletedError, NotFoundError


def _fill_volume(store, vid, n_files=40, seed=0):
    rng = np.random.default_rng(seed)
    payloads = {}
    store.add_volume(vid)
    for i in range(n_files):
        data = rng.integers(0, 256, int(rng.integers(10, 5000)),
                            dtype=np.uint8).tobytes()
        nid = i + 1
        payloads[nid] = data
        n = Needle(id=nid, cookie=0xABC0 + i, data=data,
                   name=f"f{i}.bin".encode())
        n.set_flags_from_fields()
        store.write_volume_needle(vid, n)
    return payloads


def test_store_ec_end_to_end(tmp_path):
    store = Store([str(tmp_path / "d1")], coder=make_coder("cpu"))
    payloads = _fill_volume(store, 1)

    base = store.generate_ec_shards(1)
    assert os.path.exists(base + ".ecx")
    for i in range(14):
        assert os.path.exists(base + layout.shard_ext(i))

    # unload normal volume, mount EC shards (all local at first)
    store.delete_volume(1)
    store.mount_ec_shards("", 1, list(range(14)))
    ev = store.find_ec_volume(1)
    assert ev.shard_bits().shard_id_count() == 14

    for nid, data in payloads.items():
        n = store.read_ec_shard_needle(1, nid, cookie=0xABC0 + nid - 1)
        assert n.data == data, f"needle {nid}"

    # degrade: unmount 4 shards AND delete their files -> reconstruction path
    victims = [0, 3, 7, 11]
    store.unmount_ec_shards(1, victims)
    for sid in victims:
        os.remove(base + layout.shard_ext(sid))
    for nid, data in payloads.items():
        n = store.read_ec_shard_needle(1, nid)
        assert n.data == data, f"degraded needle {nid}"

    # rebuild the killed shards and remount: reads are local again
    generated = ecenc.rebuild_ec_files(base, store.coder)
    assert sorted(generated) == victims
    store.mount_ec_shards("", 1, victims)
    assert store.find_ec_volume(1).shard_bits().shard_id_count() == 14
    for nid, data in payloads.items():
        assert store.read_ec_shard_needle(1, nid).data == data

    # delete a needle through the EC path
    store.delete_ec_shard_needle(1, 1, cookie=0xABC0)
    with pytest.raises((NotFoundError, DeletedError)):
        store.read_ec_shard_needle(1, 1)
    store.close()


def test_store_ec_remote_reader(tmp_path):
    """Shards split across two stores; reads on store A fall back to the
    remote reader wired to store B (the volume-server RPC stand-in)."""
    a = Store([str(tmp_path / "a")], coder=make_coder("cpu"))
    b_dir = tmp_path / "b"
    b_dir.mkdir()
    payloads = _fill_volume(a, 2, n_files=10, seed=3)
    base = a.generate_ec_shards(2)
    a.delete_volume(2)

    # move shards 5..13 to store B's directory (keep .ecx on A)
    import shutil
    for sid in range(5, 14):
        shutil.move(base + layout.shard_ext(sid),
                    str(b_dir / f"2{layout.shard_ext(sid)}"))
    shutil.copy(base + ".ecx", str(b_dir / "2.ecx"))
    b = Store([str(b_dir)], coder=make_coder("cpu"))
    b.mount_ec_shards("", 2, list(range(5, 14)))
    a.mount_ec_shards("", 2, list(range(0, 5)))

    def remote_reader2(vid, shard_id, offset, size):
        ev = b.find_ec_volume(vid)
        if ev is None or shard_id not in ev.shards:
            return None
        return ev.shards[shard_id].read_at(offset, size)

    a.remote_shard_reader = remote_reader2
    for nid, data in payloads.items():
        n = a.read_ec_shard_needle(2, nid)
        assert n.data == data, f"needle {nid}"
    a.close()
    b.close()


def test_store_heartbeat(tmp_path):
    store = Store([str(tmp_path / "hb")], ip="10.0.0.1", port=9000,
                  rack="r1", data_center="dc1")
    _fill_volume(store, 5, n_files=3)
    hb = store.collect_heartbeat()
    assert hb["ip"] == "10.0.0.1" and hb["rack"] == "r1"
    assert len(hb["volumes"]) == 1
    assert hb["volumes"][0]["file_count"] == 3
    deltas = store.drain_deltas()
    assert len(deltas["new_volumes"]) == 1
    assert store.drain_deltas()["new_volumes"] == []
    store.close()


def test_degraded_recovery_parallel_survives_slow_peer(tmp_path):
    """Recovery fans out peer-shard fetches concurrently with
    first-k-wins (reference store_ec.go:328-382): one wedged peer must
    not serialize — or block — the read when enough fast shards exist."""
    import threading
    import time

    a = Store([str(tmp_path / "a")], coder=make_coder("cpu"))
    b_dir = tmp_path / "b"
    b_dir.mkdir()
    payloads = _fill_volume(a, 3, n_files=4, seed=7)
    base = a.generate_ec_shards(3)
    a.delete_volume(3)

    import shutil
    # the volume is tiny, so every needle's data lives in shard 0:
    # delete shard 0 outright (recovery is the only path), keep shard
    # 13 local on A, spread 1..12 across the "network" on B
    os.remove(base + layout.shard_ext(0))
    for sid in range(1, 13):
        shutil.move(base + layout.shard_ext(sid),
                    str(b_dir / f"3{layout.shard_ext(sid)}"))
    shutil.copy(base + ".ecx", str(b_dir / "3.ecx"))
    b = Store([str(b_dir)], coder=make_coder("cpu"))
    b.mount_ec_shards("", 3, list(range(1, 13)))
    a.mount_ec_shards("", 3, [13])

    SLOW = {1, 2}  # two wedged peers; local 13 + fast 3..12 >= k=10
    in_flight = []

    def remote_reader(vid, shard_id, offset, size):
        in_flight.append(shard_id)
        if shard_id in SLOW:
            time.sleep(8.0)
            return None
        ev = b.find_ec_volume(vid)
        if ev is None or shard_id not in ev.shards:
            return None
        return ev.shards[shard_id].read_at(offset, size)

    a.remote_shard_reader = remote_reader
    t0 = time.perf_counter()
    for nid, data in payloads.items():
        n = a.read_ec_shard_needle(3, nid)
        assert n.data == data, f"needle {nid}"
    elapsed = time.perf_counter() - t0
    # sequential fetching would block 8s on the first slow peer before
    # trying the rest; the parallel fan-out completes on the fast ones
    assert elapsed < 6.0, f"slow peer serialized recovery: {elapsed:.1f}s"
    a.close()
    b.close()


def _fill_big(store, vid, n_files=3, kb=700, seed=5):
    """Needles large enough that records straddle the 1MB small-block
    boundaries — i.e. span MULTIPLE shards' blocks."""
    rng = np.random.default_rng(seed)
    payloads = {}
    store.add_volume(vid)
    for i in range(n_files):
        data = rng.integers(0, 256, kb * 1024, dtype=np.uint8).tobytes()
        nid = i + 1
        payloads[nid] = data
        n = Needle(id=nid, cookie=0xBEE0 + i, data=data,
                   name=f"big{i}.bin".encode(), mime=b"application/x-big")
        n.set_flags_from_fields()
        store.write_volume_needle(vid, n)
    return payloads


def test_ec_subrange_meta_and_range_reads(tmp_path):
    """ec_needle_meta reads only head+tail of the record; data-range
    reads return exact slices at block boundaries and tails."""
    from seaweedfs_tpu.storage.volume import NotFoundError as NFE
    store = Store([str(tmp_path / "d1")], coder=make_coder("cpu"))
    payloads = _fill_big(store, 7)
    store.generate_ec_shards(7)
    store.delete_volume(7)
    store.mount_ec_shards("", 7, list(range(14)))

    for nid, data in payloads.items():
        n, data_size = store.ec_needle_meta(7, nid,
                                            cookie=0xBEE0 + nid - 1)
        assert data_size == len(data)
        assert n.name == f"big{nid - 1}.bin".encode()
        assert n.mime == b"application/x-big"
        assert n.data == b"", "meta read must not touch the payload"
        total = len(data)
        # head, tail, interior, whole span, and (for the later needles)
        # ranges crossing the 1MB small-block boundary between shards
        spans = [(0, 16), (total - 13, 13), (1234, 4096),
                 (0, total), (total // 2 - 100, 200)]
        for lo, ln in spans:
            got = store.read_ec_needle_data_range(7, nid, lo, ln)
            assert got == data[lo:lo + ln], (nid, lo, ln)
    with pytest.raises(NFE):
        store.ec_needle_meta(7, 1, cookie=0xDEAD)
    store.close()


def test_ec_subrange_degraded_read_is_frugal(tmp_path):
    """With a shard missing, a small range read reconstructs ~k copies
    of THAT range — not the record, not the block. The 700KB needles
    here must be servable for a few-KB range at a few-KB cost."""
    store = Store([str(tmp_path / "d1")], coder=make_coder("cpu"))
    payloads = _fill_big(store, 8)
    base = store.generate_ec_shards(8)
    store.delete_volume(8)
    store.mount_ec_shards("", 8, list(range(14)))
    ev = store.find_ec_volume(8)

    # needle 2's record crosses from shard 0's small block into shard
    # 1's; kill shard 1 so part of every later range is degraded
    victim = 1
    store.unmount_ec_shards(8, [victim])
    os.remove(base + layout.shard_ext(victim))

    counted = {"bytes": 0}
    for shard in ev.shards.values():
        orig = shard.read_at

        def wrap(offset, length, _orig=orig):
            counted["bytes"] += length
            return _orig(offset, length)

        shard.read_at = wrap

    data = payloads[2]
    lo, ln = len(data) - 4096, 2048  # tail range, lives in shard 1
    got = store.read_ec_needle_data_range(8, 2, lo, ln)
    assert got == data[lo:lo + ln]
    # k shards x ~2KB for reconstruction plus meta slack — nowhere near
    # the 700KB record (let alone the 1MB block) the old path decoded
    assert counted["bytes"] < 120 * 1024, counted["bytes"]

    counted["bytes"] = 0
    n, data_size = store.ec_needle_meta(8, 2)
    assert data_size == len(data)
    assert counted["bytes"] < 80 * 1024, counted["bytes"]
    store.close()
