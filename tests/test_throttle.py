"""Volume-server in-flight throttling + file-size limit tests
(round-2/3 verdict gap #4; reference weed/server/volume_server.go:23-30,
volume_server_handlers.go inFlight*DataLimitCond)."""

import threading
import time

import pytest

from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call
from seaweedfs_tpu.utils.limiter import InFlightLimiter


# ---------- InFlightLimiter unit ----------

def test_limiter_basic():
    lim = InFlightLimiter(100, timeout=0.2)
    assert lim.try_acquire(60)
    assert lim.try_acquire(40)
    assert lim.in_flight == 100
    # over the cap: times out while the pipe is full
    t0 = time.monotonic()
    assert not lim.try_acquire(1)
    assert time.monotonic() - t0 >= 0.18
    lim.release(60)
    assert lim.try_acquire(1)
    lim.release(41)
    assert lim.in_flight == 0


def test_limiter_oversized_single_request_admitted_alone():
    """A single payload larger than the whole cap goes through when the
    pipe is empty (matching the reference's compare-before-add)."""
    lim = InFlightLimiter(100, timeout=0.2)
    assert lim.try_acquire(500)
    assert not lim.try_acquire(1)  # pipe fully occupied
    lim.release(500)
    assert lim.try_acquire(1)


def test_limiter_unblocks_waiters():
    lim = InFlightLimiter(100, timeout=5.0)
    assert lim.try_acquire(100)
    got = []

    def waiter():
        got.append(lim.try_acquire(50))

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.05)
    assert lim.waiters == 1
    lim.release(100)
    th.join(timeout=2)
    assert got == [True]


def test_limiter_unlimited():
    lim = InFlightLimiter(0)
    assert lim.try_acquire(1 << 40)


# ---------- against a live volume server ----------

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer()
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url,
                      concurrent_upload_limit_mb=1,
                      concurrent_download_limit_mb=1,
                      file_size_limit_mb=2,
                      inflight_timeout=0.5)
    vs.start()
    time.sleep(0.05)
    yield master, vs
    vs.stop()
    master.stop()


def _assign(master):
    status, body, _ = http_call(
        "GET", f"http://{master.url}/dir/assign")
    import json
    return json.loads(body)


def test_file_size_limit_413(cluster):
    master, vs = cluster
    a = _assign(master)
    status, body, _ = http_call(
        "POST", f"http://{a['url']}/{a['fid']}", body=b"x" * (3 << 20))
    assert status == 413


def test_upload_within_limits_still_works(cluster):
    master, vs = cluster
    a = _assign(master)
    status, _, _ = http_call(
        "POST", f"http://{a['url']}/{a['fid']}", body=b"y" * 1000)
    assert status == 201
    status, body, _ = http_call("GET", f"http://{a['url']}/{a['fid']}")
    assert status == 200 and body == b"y" * 1000


def test_concurrent_big_puts_shed_with_429(cluster):
    """With a 1MB in-flight cap and a 0.5s wait, 4 concurrent ~0.9MB
    PUTs cannot all be in flight: at least one succeeds, the pipe never
    holds more than the cap, and the stragglers get 429 (not OOM)."""
    master, vs = cluster
    payload = b"z" * (900 * 1024)
    results = []
    lock = threading.Lock()

    def put():
        a = _assign(master)
        status, _, _ = http_call(
            "POST", f"http://{a['url']}/{a['fid']}", body=payload)
        with lock:
            results.append(status)

    threads = [threading.Thread(target=put) for _ in range(4)]
    for t in threads:
        t.start()
    peak = 0
    deadline = time.time() + 5
    while any(t.is_alive() for t in threads) and time.time() < deadline:
        peak = max(peak, vs.upload_limiter.in_flight)
        time.sleep(0.002)
    for t in threads:
        t.join(timeout=10)
    assert sorted(set(results)) and all(s in (201, 429) for s in results)
    assert 201 in results
    # the cap held: never more than one 0.9MB payload accounted at once
    assert peak <= 1024 * 1024
    # after the dust settles the accounting drains to zero
    time.sleep(0.1)
    assert vs.upload_limiter.in_flight == 0


def test_download_accounting_drains(cluster):
    master, vs = cluster
    a = _assign(master)
    status, _, _ = http_call(
        "POST", f"http://{a['url']}/{a['fid']}", body=b"d" * 500_000)
    assert status == 201
    for _ in range(3):
        status, body, _ = http_call("GET", f"http://{a['url']}/{a['fid']}")
        assert status == 200 and len(body) == 500_000
    time.sleep(0.05)
    assert vs.download_limiter.in_flight == 0
