"""Redis-protocol FilerStore over a real socket (round-2/3 verdict
gap #10: prove the FilerStore SPI against a network database protocol,
not just embedded engines). Reference: weed/filer/redis2/redis_store.go.
The server side is MiniRedisServer — a RESP2 stub — so the full client
protocol (framing, bulk strings, sorted-set lex ranges) is exercised
end-to-end without a Redis install."""

import time

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.filer.redis_store import (MiniRedisServer,
                                             RedisFilerStore, RespClient)
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call


@pytest.fixture
def redis():
    srv = MiniRedisServer().start()
    yield srv
    srv.stop()


def test_resp_client_protocol(redis):
    c = RespClient(redis.host, redis.port)
    assert c.command("PING") == "PONG"
    assert c.command("SET", "k1", b"\x00binary\r\nsafe") == "OK"
    assert c.command("GET", "k1") == b"\x00binary\r\nsafe"
    assert c.command("GET", "nope") is None
    assert c.command("DEL", "k1") == 1
    assert c.command("ZADD", "z", 0, "alpha") == 1
    c.command("ZADD", "z", 0, "beta")
    c.command("ZADD", "z", 0, "gamma")
    assert c.command("ZRANGEBYLEX", "z", "-", "+") == \
        [b"alpha", b"beta", b"gamma"]
    assert c.command("ZRANGEBYLEX", "z", "(alpha", "+") == \
        [b"beta", b"gamma"]
    assert c.command("ZRANGEBYLEX", "z", "[beta", "[beta") == [b"beta"]
    assert c.command("ZREM", "z", "beta") == 1
    with pytest.raises(RuntimeError):
        c.command("NOSUCH")
    c.close()


def test_redis_store_contract(redis):
    """The same contract the embedded stores pass (tests/test_filer.py
    test_store_contract), over the wire."""
    s = make_store("redis", host=redis.host, port=redis.port)
    assert isinstance(s, RedisFilerStore)
    e = Entry("/a/b/file.txt", Attr(mtime=1.0, file_size=5))
    s.insert_entry(e)
    got = s.find_entry("/a/b/file.txt")
    assert got is not None and got.attr.file_size == 5

    s.insert_entry(Entry("/a/b/other.txt"))
    s.insert_entry(Entry("/a/b/sub", Attr(is_directory=True)))
    s.insert_entry(Entry("/a/b/sub/deep.txt"))
    names = [x.name for x in s.list_directory_entries("/a/b")]
    assert names == ["file.txt", "other.txt", "sub"]
    names = [x.name for x in s.list_directory_entries("/a/b", prefix="o")]
    assert names == ["other.txt"]
    names = [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt")]
    assert names == ["other.txt", "sub"]
    names = [x.name for x in s.list_directory_entries(
        "/a/b", start_name="file.txt", include_start=True)]
    assert names == ["file.txt", "other.txt", "sub"]

    s.delete_folder_children("/a/b")
    assert s.list_directory_entries("/a/b") == []
    # recursive: the nested child went too
    assert s.find_entry("/a/b/sub/deep.txt") is None

    s.kv_put(b"conf", b"xyz")
    assert s.kv_get(b"conf") == b"xyz"
    assert s.kv_get(b"missing") is None
    s.kv_delete(b"conf")
    assert s.kv_get(b"conf") is None
    s.close()


def test_filer_server_on_redis_store(redis, tmp_path):
    """A full filer (HTTP plane + chunking) with redis metadata: write,
    list, read, rename, delete — and the metadata actually lives in the
    redis server (a second store sees it)."""
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url, store="redis",
                     store_dir=f"{redis.host}:{redis.port}")
    fs.start()
    time.sleep(0.1)
    try:
        payload = b"stored through redis metadata" * 300
        status, _, _ = http_call("POST", f"http://{fs.url}/dir/doc.bin",
                                 body=payload)
        assert status < 300
        status, body, _ = http_call("GET", f"http://{fs.url}/dir/doc.bin")
        assert status == 200 and body == payload

        # independent client sees the same metadata over the wire
        other = RedisFilerStore(redis.host, redis.port)
        e = other.find_entry("/dir/doc.bin")
        assert e is not None and e.file_size() == len(payload)
        assert e.chunks  # chunked through the volume layer
        other.close()

        status, _, _ = http_call(
            "POST", f"http://{fs.url}/__api/rename",
            json_body={"from": "/dir/doc.bin", "to": "/dir/doc2.bin"})
        assert status == 200
        status, body, _ = http_call("GET",
                                    f"http://{fs.url}/dir/doc2.bin")
        assert status == 200 and body == payload
        status, _, _ = http_call("DELETE", f"http://{fs.url}/dir/doc2.bin")
        assert status < 300
        status, _, _ = http_call("GET", f"http://{fs.url}/dir/doc2.bin")
        assert status == 404
    finally:
        fs.stop()
        vs.stop()
        master.stop()
