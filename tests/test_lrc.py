"""LRC(10,2,2): the coder's information-theoretic contract, the repair
planner, and the mixed-code cluster.

Four layers:

1. the code itself — brute-force EVERY erasure pattern of size <= 4
   (1470 of them) against the maximal-recoverability criterion for the
   (k=10, l=2, g=2) topology: a pattern decodes iff each local group
   absorbs one loss with its own parity and the remaining losses fit
   the g=2 global budget.  Recoverable patterns must round-trip
   bit-identically; unrecoverable ones must raise, never fabricate;
2. the planner — every single lost shard inside a local group (data
   0-9, local parities 10-11) plans a group-LOCAL repair reading the 5
   surviving group members; global parities plan a k=10 global decode;
   decode-after-repair is an identity;
3. the on-disk plumbing — .vif CodeSpec persistence, shard-file
   geometry shared with RS (14 files, same extensions);
4. the mixed-code cluster — RS and LRC volumes coexisting on ONE
   store: per-volume coder dispatch, degraded reads with the correct
   per-family strategy (LRC counts a "local" recovery), scrub with
   group-local parity verification, and per-volume rebuild.
"""

import itertools
import os

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import (LrcScheme, RSScheme, make_coder,
                                        scheme_from_dict, scheme_to_dict)
from seaweedfs_tpu.ops.lrc import DEFAULT_LRC_SCHEME, LrcCoder
from seaweedfs_tpu.storage.erasure_coding import ec_volume as ecv
from seaweedfs_tpu.storage.erasure_coding import encoder as ecenc
from seaweedfs_tpu.storage.erasure_coding import layout

SPEC = DEFAULT_LRC_SCHEME
K, TOTAL = SPEC.data_shards, SPEC.total_shards
GROUPS = [set(SPEC.group_members(g)) for g in range(SPEC.local_groups)]
GLOBALS = set(SPEC.global_parity_ids())


def _mr_recoverable(erased: set) -> bool:
    """The maximal-recoverability criterion for a basic pyramid
    LRC(k, l, g): each local group's parity absorbs one of its own
    losses; everything left (extra in-group losses + lost globals)
    must fit the g global parities."""
    need = sum(max(0, len(erased & grp) - 1) for grp in GROUPS)
    return need + len(erased & GLOBALS) <= SPEC.global_parities


def _shards(coder, n_bytes=64, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=(K, n_bytes), dtype=np.uint8)
    return coder.encode([data[i].tobytes() for i in range(K)])


# ------------------------------------------------- the code contract

def test_every_small_erasure_pattern_matches_mr_criterion():
    """All 1470 patterns of <= 4 erasures: plan_rebuild succeeds
    exactly on the information-theoretically recoverable ones."""
    coder = LrcCoder()
    n_ok = n_bad = 0
    for size in (1, 2, 3, 4):
        for erased in itertools.combinations(range(TOTAL), size):
            erased_set = set(erased)
            present = [s for s in range(TOTAL) if s not in erased_set]
            want = _mr_recoverable(erased_set)
            try:
                coder.plan_rebuild(present, sorted(erased_set))
                got = True
            except ValueError:
                got = False
            assert got == want, (sorted(erased_set), want)
            n_ok += want
            n_bad += not want
    # sanity on the brute force itself: both verdicts occurred, and
    # every pattern RS(10,4) could decode minus the LRC-unrecoverable
    # ones is the documented trade
    assert n_ok + n_bad == 14 + 91 + 364 + 1001
    assert n_bad > 0  # LRC gives up some 3/4-erasure patterns vs RS


def test_recoverable_patterns_round_trip_bit_identically():
    """Actual byte reconstruction for every recoverable pattern of
    size <= 2 plus a sample of 3/4-sized ones."""
    coder = LrcCoder()
    full = _shards(coder, seed=1)
    patterns = [p for size in (1, 2)
                for p in itertools.combinations(range(TOTAL), size)]
    patterns += [(0, 5, 12), (1, 2, 13), (0, 1, 12, 13), (3, 4, 6, 7),
                 (0, 5, 10, 11)]
    for erased in patterns:
        if not _mr_recoverable(set(erased)):
            continue
        holes = [None if i in erased else bytes(s)
                 for i, s in enumerate(full)]
        got = coder.reconstruct(holes)
        assert [bytes(s) for s in got] == [bytes(s) for s in full], \
            erased


def test_unrecoverable_pattern_raises_never_fabricates():
    coder = LrcCoder()
    full = _shards(coder, seed=2)
    # three losses in one group exceed its parity + the global budget
    erased = (0, 1, 2, 3)
    assert not _mr_recoverable(set(erased))
    holes = [None if i in erased else bytes(s)
             for i, s in enumerate(full)]
    with pytest.raises(ValueError):
        coder.reconstruct(holes)


def test_encode_matches_scalar_reference():
    """The batched GF matmul encode against the O(m*k*n) double loop."""
    from seaweedfs_tpu.ops import gf256

    coder = LrcCoder()
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(K, 128), dtype=np.uint8)
    fast = coder.encode_array(data)
    for r in range(coder._parity.shape[0]):
        want = bytearray(data.shape[1])
        for c in range(K):
            coef = int(coder._parity[r, c])
            for j in range(data.shape[1]):
                want[j] ^= gf256.gf_mul(coef, int(data[c, j]))
        assert bytes(fast[r]) == bytes(want), f"parity row {r}"


# ------------------------------------------------------- the planner

def test_single_group_shard_loss_plans_local_repair():
    """Every shard living in a local group (data + local parities)
    repairs from exactly its 5 surviving group members."""
    coder = LrcCoder()
    for sid in range(TOTAL):
        present = [s for s in range(TOTAL) if s != sid]
        st = coder.repair_strategy(present, [sid])
        grp = next((g for g in range(SPEC.local_groups)
                    if sid in GROUPS[g]), None)
        if grp is not None:
            assert st["strategy"] == "local", (sid, st)
            assert set(st["sources"]) == GROUPS[grp] - {sid}, (sid, st)
            assert st["reads"] == SPEC.group_size, (sid, st)
        else:  # a global parity: full decode, k columns
            assert st["strategy"] == "global", (sid, st)
            assert st["reads"] == K, (sid, st)


def test_decode_after_repair_identity():
    """Repair a shard via its plan, then lose OTHER shards and decode:
    the repaired shard must behave exactly like the original."""
    coder = LrcCoder()
    full = [bytes(s) for s in _shards(coder, seed=4)]
    # repair shard 7 group-locally
    src, mat = coder.plan_rebuild(
        [s for s in range(TOTAL) if s != 7], [7])
    rec = coder.reconstruct_rows(
        np.stack([np.frombuffer(full[s], dtype=np.uint8)
                  for s in src]), mat)
    repaired = list(full)
    repaired[7] = rec[0].tobytes()
    assert repaired[7] == full[7]
    # now lose two data shards + a global and decode from the repaired set
    holes = [None if i in (0, 5, 12) else s
             for i, s in enumerate(repaired)]
    got = coder.reconstruct(holes)
    assert [bytes(s) for s in got] == full


def test_plan_rebuild_sources_helper_prefers_plan():
    """encoder.plan_rebuild_sources routes through plan_rebuild for
    LRC (narrow sources) and rebuild_matrix column-filtering for RS."""
    lrc, rs = LrcCoder(), make_coder("cpu")
    present = [s for s in range(TOTAL) if s != 3]
    src, mat = ecenc.plan_rebuild_sources(lrc, present, [3])
    assert len(src) == SPEC.group_size  # 4 group data + the local parity
    assert mat.shape == (1, len(src))
    src_rs, mat_rs = ecenc.plan_rebuild_sources(rs, present, [3])
    assert len(src_rs) == K
    assert mat_rs.shape == (1, K)


# --------------------------------------------------- scheme plumbing

def test_scheme_identity_and_dict_round_trip():
    lrc, rs = LrcScheme(), RSScheme(10, 4)
    assert lrc != rs and rs != lrc  # type-identity, not field equality
    assert lrc.total_shards == rs.total_shards == layout.TOTAL_SHARDS_COUNT
    d = scheme_to_dict(lrc)
    assert d["family"] == "lrc"
    back = scheme_from_dict(d)
    assert isinstance(back, LrcScheme) and back == lrc
    assert isinstance(scheme_from_dict(None), RSScheme)
    assert isinstance(scheme_from_dict(scheme_to_dict(rs)), RSScheme)


def test_lrc_coder_registered_and_scheme_forced():
    c = make_coder("lrc")
    assert isinstance(c, LrcCoder)
    assert isinstance(c.scheme, LrcScheme)
    mt = make_coder("lrc-mt")
    assert isinstance(mt, LrcCoder) and mt.workers >= 1


# ---------------------------------------------- mixed-code cluster

def _fill_volume(store, vid, n_files=12, seed=0):
    from seaweedfs_tpu.storage.needle import Needle

    rng = np.random.default_rng(seed)
    payloads = {}
    store.add_volume(vid)
    for i in range(n_files):
        data = rng.integers(0, 256, int(rng.integers(100, 4000)),
                            dtype=np.uint8).tobytes()
        nid = i + 1
        payloads[nid] = data
        n = Needle(id=nid, cookie=0xC0DE + i, data=data,
                   name=f"f{i}.bin".encode())
        n.set_flags_from_fields()
        store.write_volume_needle(vid, n)
    return payloads


def test_mixed_code_cluster_on_one_store(tmp_path):
    """RS and LRC volumes coexisting on one store: per-volume CodeSpec
    persistence and coder dispatch, degraded reads with the correct
    per-family strategy, scrub (group-local parity verification for
    LRC), and per-volume rebuild — concurrently mounted."""
    from seaweedfs_tpu.scrub import Scrubber
    from seaweedfs_tpu.storage.store import Store

    store = Store([str(tmp_path / "d")], coder=make_coder("cpu"))
    pay_rs = _fill_volume(store, 1, seed=1)
    pay_lrc = _fill_volume(store, 2, seed=2)

    base_rs = store.generate_ec_shards(1)
    base_lrc = store.generate_ec_shards(2, code="lrc")
    # CodeSpec persisted per volume
    assert ecv.read_volume_info(base_rs).get("code", {}) in ({}, None) \
        or ecv.read_volume_info(base_rs)["code"].get("family", "rs") == "rs"
    assert ecv.read_volume_info(base_lrc)["code"]["family"] == "lrc"

    store.delete_volume(1)
    store.delete_volume(2)
    store.mount_ec_shards("", 1, list(range(layout.TOTAL_SHARDS_COUNT)))
    store.mount_ec_shards("", 2, list(range(layout.TOTAL_SHARDS_COUNT)))

    # per-volume coder dispatch off the persisted scheme
    ev_rs, ev_lrc = store.find_ec_volume(1), store.find_ec_volume(2)
    assert not isinstance(store.coder_for(ev_rs), LrcCoder)
    assert isinstance(store.coder_for(ev_lrc), LrcCoder)
    assert isinstance(ev_lrc.scheme, LrcScheme)

    # healthy reads on both
    for nid, data in pay_rs.items():
        assert store.read_ec_shard_needle(1, nid).data == data
    for nid, data in pay_lrc.items():
        assert store.read_ec_shard_needle(2, nid).data == data

    # scrub while healthy: each volume verifies against ITS generator
    # (the LRC volume's local parities check group-locally)
    scrubber = Scrubber(store, rate_bytes_per_sec=0)
    out = scrubber.run_once()
    assert out["corruptions"] == [], out
    codes = {rep["volume_id"]: rep.get("code")
             for rep in out["volumes"] if rep.get("ec")}
    assert codes.get(2) == "LrcScheme", codes
    assert codes.get(1) != "LrcScheme", codes

    # degrade BOTH volumes: kill a group-0 data shard on each
    for vid, base in ((1, base_rs), (2, base_lrc)):
        store.unmount_ec_shards(vid, [0])
        os.remove(base + layout.shard_ext(0))
    before = dict(store.ec_recover_stats)
    for nid, data in pay_rs.items():
        assert store.read_ec_shard_needle(1, nid).data == data
    for nid, data in pay_lrc.items():
        assert store.read_ec_shard_needle(2, nid).data == data
    # the LRC volume's recoveries went through the local-group plan
    assert store.ec_recover_stats["local"] > before.get("local", 0)

    # rebuild each volume with ITS coder; reads are local again
    for vid, base, ev in ((1, base_rs, ev_rs), (2, base_lrc, ev_lrc)):
        stats: dict = {}
        generated = ecenc.rebuild_ec_files(base, store.coder_for(ev),
                                           stats=stats)
        assert generated == [0]
        store.mount_ec_shards("", vid, [0])
        if vid == 2:  # the LRC rebuild read the group, not k columns
            assert len(stats["sources"]) == SPEC.group_size
    for nid, data in pay_lrc.items():
        assert store.read_ec_shard_needle(2, nid).data == data
    store.close()
