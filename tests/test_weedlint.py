"""weedlint: the AST invariant checker (tools/weedlint).

Three layers of coverage:

1. per-rule fixtures — for each rule a violating snippet, a clean
   counterpart, and a suppressed variant, run through check_source;
2. the engine — baseline capture/round-trip, the consuming-multiset
   new-violation filter, --diff against a synthetic two-commit git
   repo, CLI exit codes;
3. the tree gate — the real repository lints clean against the
   checked-in baseline (THE tier-1 invariant this PR adds), inside the
   <5s budget, and the baseline has burned down >=60 entries from the
   initial capture frozen at tools/weedlint/baseline_initial.json.
"""

import json
import subprocess
import sys
import time
from collections import Counter
from pathlib import Path

import pytest

from tools.weedlint import engine
from tools.weedlint.rules import RULES, check_source

REPO = Path(__file__).resolve().parent.parent


def rules_of(src: str, path: str = "seaweedfs_tpu/x.py") -> list:
    return [v.rule for v in check_source(path, src)]


# ------------------------------------------------- per-rule fixtures

CASES = {
    "raw-clock": {
        "bad": "import time\n\ndef f():\n    return time.monotonic()\n",
        "clean": ("from seaweedfs_tpu.utils import clockctl\n\n"
                  "def f():\n    return clockctl.monotonic()\n"),
    },
    "raw-histogram-timer": {
        "bad": ("import time\n\ndef f():\n"
                "    return time.perf_counter()\n"),
        "clean": ("from seaweedfs_tpu.utils import clockctl\n\n"
                  "def f():\n    return clockctl.monotonic()\n"),
    },
    "raw-http": {
        "bad": ("import urllib.request\n\ndef f(url):\n"
                "    return urllib.request.urlopen(url).read()\n"),
        "clean": ("from seaweedfs_tpu.utils.httpd import http_call\n\n"
                  "def f(url):\n"
                  "    return http_call('GET', url)[1]\n"),
    },
    "lock-across-blocking": {
        "bad": ("import time\nfrom seaweedfs_tpu.utils.httpd import "
                "http_call\nlock = object()\n\ndef f():\n"
                "    with lock:\n"
                "        http_call('GET', 'http://x/')\n"),
        "clean": ("from seaweedfs_tpu.utils.httpd import http_call\n"
                  "lock = object()\n\ndef f():\n"
                  "    with lock:\n        x = 1\n"
                  "    http_call('GET', 'http://x/')\n"),
    },
    "swallowed-exit": {
        "bad": ("def gen():\n    try:\n        yield 1\n"
                "    except BaseException:\n        pass\n"),
        "clean": ("def gen():\n    try:\n        yield 1\n"
                  "    except Exception:\n        pass\n"),
    },
    "header-literal": {
        "bad": "HEADERS = {'X-Weed-Deadline': '5'}\n",
        "clean": ("from seaweedfs_tpu.utils import headers\n"
                  "HEADERS = {headers.DEADLINE: '5'}\n"),
    },
    "persistent-socket-timeout": {
        "bad": ("import socket\n\ndef connect(h, p):\n"
                "    return socket.create_connection((h, p), timeout=5)\n"),
        "clean": ("import socket\n\ndef connect(h, p):\n"
                  "    s = socket.create_connection((h, p), timeout=5)\n"
                  "    s.settimeout(None)\n    return s\n"),
    },
    "unbounded-pool": {
        "bad": "import queue\n\nq = queue.Queue()\n",
        "clean": "import queue\n\nq = queue.Queue(maxsize=64)\n",
    },
    "raw-device-discovery": {
        "bad": "import jax\n\ndef f():\n    return jax.devices()\n",
        "clean": ("from seaweedfs_tpu.parallel import mesh\n\n"
                  "def f():\n    return mesh.devices()\n"),
    },
    "unbounded-body-read": {
        "bad": ("def handler(req):\n"
                "    return len(req.body)\n"),
        "clean": ("def handler(req):\n"
                  "    n = 0\n"
                  "    while True:\n"
                  "        piece = req.stream.read(65536)\n"
                  "        if not piece:\n"
                  "            return n\n"
                  "        n += len(piece)\n"),
    },
    "unnamed-thread": {
        "bad": ("import threading\n\n"
                "def f(fn):\n"
                "    threading.Thread(target=fn, daemon=True).start()\n"),
        "clean": ("import threading\n\n"
                  "def f(fn):\n"
                  "    threading.Thread(target=fn, daemon=True,\n"
                  "                     name='worker').start()\n"),
    },
    "filer-cache-bypass": {
        "path": "seaweedfs_tpu/server/filer_server.py",
        "bad": ("def h(self, path):\n"
                "    return self.filer.store.find_entry(path)\n"),
        "clean": ("def h(self, path):\n"
                  "    return self.filer.find_entry(path)\n"),
    },
    "hot-path-bytes-copy": {
        "path": "seaweedfs_tpu/storage/x.py",
        "bad": ("def serve(blob):\n"
                "    return bytes(blob)\n"),
        "clean": ("def serve(blob):\n"
                  "    return memoryview(blob)\n"),
    },
    "hardcoded-shard-count": {
        "path": "seaweedfs_tpu/storage/erasure_coding/x.py",
        "bad": ("def shard_files(base):\n"
                "    return [base + str(i) for i in range(14)]\n"),
        "clean": ("from seaweedfs_tpu.storage.erasure_coding import "
                  "layout\n\n"
                  "def shard_files(base):\n"
                  "    return [base + str(i)\n"
                  "            for i in range(layout.TOTAL_SHARDS_COUNT)]"
                  "\n"),
    },
    "lease-wall-clock": {
        "bad": ("import time\n\ndef grant(vid, ttl):\n"
                "    lease_expires_at = time.time() + ttl\n"
                "    return {'vid': vid, 'expires_at': lease_expires_at}"
                "\n"),
        "clean": ("from seaweedfs_tpu.utils import clockctl\n\n"
                  "def grant(vid, ttl):\n"
                  "    return {'vid': vid,\n"
                  "            'expires_at': clockctl.now() + ttl}\n"),
    },
    "ambient-scope-loss": {
        "bad": ("from seaweedfs_tpu.utils.tracing import current_span\n\n"
                "def f(pool):\n"
                "    def work():\n        return current_span()\n"
                "    pool.submit(work)\n"),
        "clean": ("from seaweedfs_tpu.utils.tracing import (current_span,"
                  " span_scope)\n\n"
                  "def f(pool):\n"
                  "    span = current_span()\n"
                  "    def work():\n"
                  "        with span_scope(span):\n"
                  "            return span\n"
                  "    pool.submit(work)\n"),
    },
    "ring-epoch-forward": {
        "bad": ("def adopt(self, ring):\n"
                "    cur = self.shard_ring\n"
                "    if cur is None or ring.epoch == cur.epoch:\n"
                "        self.shard_ring = ring\n"),
        "clean": ("def adopt(self, ring):\n"
                  "    cur = self.shard_ring\n"
                  "    if cur is None or ring.epoch > cur.epoch:\n"
                  "        self.shard_ring = ring\n"),
    },
    "tier-move-background": {
        "bad": ("from seaweedfs_tpu.storage.tiering import "
                "demote_volume\n\n"
                "def apply(move):\n"
                "    demote_volume(move['url'], move['vid'], 'ec')\n"),
        "clean": ("from seaweedfs_tpu.qos import BACKGROUND, "
                  "class_scope\n"
                  "from seaweedfs_tpu.storage.tiering import "
                  "demote_volume\n\n"
                  "def apply(move):\n"
                  "    with class_scope(BACKGROUND):\n"
                  "        demote_volume(move['url'], move['vid'], "
                  "'ec')\n"),
    },
}


def _case_path(rule: str) -> str:
    # path-scoped rules (e.g. filer-cache-bypass) carry the file the
    # fixture must pretend to live in
    return CASES[rule].get("path", "seaweedfs_tpu/x.py")


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_flags_violation(rule):
    assert rule in rules_of(CASES[rule]["bad"], path=_case_path(rule)), \
        f"{rule}: violating fixture not flagged"


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_passes_clean_counterpart(rule):
    assert rule not in rules_of(CASES[rule]["clean"],
                                path=_case_path(rule)), \
        f"{rule}: clean fixture wrongly flagged"


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_suppressible_inline(rule):
    bad = CASES[rule]["bad"]
    flagged = check_source(_case_path(rule), bad)
    line_no = next(v.line for v in flagged if v.rule == rule)
    lines = bad.splitlines(keepends=True)
    lines[line_no - 1] = (lines[line_no - 1].rstrip("\n")
                          + f"  # weedlint: disable={rule}\n")
    assert rule not in rules_of("".join(lines), path=_case_path(rule)), \
        f"{rule}: inline suppression ignored"


def test_every_rule_has_a_fixture():
    assert set(CASES) == set(RULES)


# ------------------------------------ rule subtleties worth pinning

def test_suppression_comment_block_above():
    """The directive may sit anywhere in the contiguous comment block
    above a multi-line statement (the httpd.py idiom)."""
    src = ("import socket\n\ndef connect(h, p):\n"
           "    # weedlint: disable=persistent-socket-timeout — managed\n"
           "    # per-request by the caller\n"
           "    return socket.create_connection((h, p),\n"
           "                                    timeout=5)\n")
    assert "persistent-socket-timeout" not in rules_of(src)


def test_swallowed_exit_shielded_by_prior_generatorexit_handler():
    """A broad handler AFTER `except GeneratorExit: raise` can never
    see GeneratorExit and must not be flagged (the sim _reply_chain
    shape)."""
    src = ("def gen():\n    try:\n        yield 1\n"
           "    except GeneratorExit:\n        raise\n"
           "    except BaseException as e:\n        err = e\n")
    assert "swallowed-exit" not in rules_of(src)


def test_swallowed_exit_flags_yield_in_finally():
    src = ("def gen():\n    try:\n        yield 1\n"
           "    finally:\n        yield 2\n")
    assert "swallowed-exit" in rules_of(src)


def test_raw_clock_catches_aliased_imports():
    assert "raw-clock" in rules_of(
        "from time import sleep as snooze\n\ndef f():\n    snooze(1)\n")
    assert "raw-clock" in rules_of(
        "import time as t\n\ndef f():\n    return t.time()\n")


def test_rule_home_files_are_exempt():
    assert "raw-clock" not in rules_of(
        "import time\nx = time.time()\n",
        path="seaweedfs_tpu/utils/clockctl.py")
    assert "header-literal" not in rules_of(
        "D = 'X-Weed-Deadline'\n",
        path="seaweedfs_tpu/utils/headers.py")
    assert "raw-device-discovery" not in rules_of(
        "import jax\nd = jax.devices()\n",
        path="seaweedfs_tpu/parallel/mesh.py")


def test_raw_histogram_timer_scoped_to_package():
    """perf_counter is only a violation inside seaweedfs_tpu/ — bench
    drivers in tools/ measure wall time on purpose — and clockctl.py
    itself (the sanctioned home) is exempt."""
    src = "import time\n\ndef f():\n    return time.perf_counter()\n"
    assert "raw-histogram-timer" in rules_of(src)
    assert "raw-histogram-timer" not in rules_of(
        src, path="tools/bench_thing.py")
    assert "raw-histogram-timer" not in rules_of(
        src, path="seaweedfs_tpu/utils/clockctl.py")
    assert "raw-histogram-timer" in rules_of(
        "from time import perf_counter as pc\n\ndef f():\n"
        "    return pc()\n")


def test_raw_device_discovery_catches_aliased_imports():
    assert "raw-device-discovery" in rules_of(
        "from jax import devices as dv\n\ndef f():\n    return dv()\n")
    assert "raw-device-discovery" in rules_of(
        "import jax as j\n\ndef f():\n    return j.local_devices()\n")


def test_unbounded_body_read_variants():
    """The rule hunts all three shapes — req.body, .readall(), bare
    stream-ish .read() — but leaves sized reads and non-stream
    receivers alone (a local file handle reads to EOF legitimately)."""
    assert "unbounded-body-read" in rules_of(
        "def h(sock):\n    return sock.read()\n")
    assert "unbounded-body-read" in rules_of(
        "def h(req):\n    return req.stream.readall()\n")
    assert "unbounded-body-read" not in rules_of(
        "def h(req):\n    return req.stream.read(4096)\n")
    assert "unbounded-body-read" not in rules_of(
        "def h(path):\n    with open(path) as f:\n"
        "        return f.read()\n")
    # the streaming reader's home implements the contract
    assert "unbounded-body-read" not in rules_of(
        "def h(req):\n    return req.body\n",
        path="seaweedfs_tpu/utils/httpd.py")


def test_filer_cache_bypass_scoping():
    """The rule bites only inside server/filer_server.py, and the raw
    row-level API (.store.inner.find_entry) stays legal there."""
    bad = ("def h(self, path):\n"
           "    return self.filer.store.find_entry(path)\n")
    assert "filer-cache-bypass" not in rules_of(bad)  # other files
    assert "filer-cache-bypass" not in rules_of(
        ("def h(self, path):\n"
         "    return self.filer.store.inner.find_entry(path)\n"),
        path="seaweedfs_tpu/server/filer_server.py")


def test_hot_path_bytes_copy_scoping():
    """The rule bites only under storage/ and server/, only on
    payload-named buffers, and catches the slice spellings too —
    bytes(x[a:b]) and the bare full-slice copy x[:]."""
    bad = "def f(blob):\n    return bytes(blob)\n"
    # outside the read data plane: legal
    assert "hot-path-bytes-copy" not in rules_of(bad)
    assert "hot-path-bytes-copy" not in rules_of(
        bad, path="seaweedfs_tpu/filer/x.py")
    # non-payload names: legal (bytes(n) preallocation, bytes(fid))
    assert "hot-path-bytes-copy" not in rules_of(
        "def f(fid):\n    return bytes(fid)\n",
        path="seaweedfs_tpu/storage/x.py")
    # bytes of a slice of a payload: flagged
    assert "hot-path-bytes-copy" in rules_of(
        "def f(blob, a, b):\n    return bytes(blob[a:b])\n",
        path="seaweedfs_tpu/server/x.py")
    # full-slice copy: flagged; a bounded slice is not a full copy
    assert "hot-path-bytes-copy" in rules_of(
        "def f(data):\n    return data[:]\n",
        path="seaweedfs_tpu/storage/x.py")
    assert "hot-path-bytes-copy" not in rules_of(
        "def f(data, n):\n    return data[:n]\n",
        path="seaweedfs_tpu/storage/x.py")
    # the transport home keeps its sanctioned materializations
    assert "hot-path-bytes-copy" not in rules_of(
        bad, path="seaweedfs_tpu/utils/httpd.py")


def test_lease_wall_clock_shapes_and_scoping():
    """The rule hunts every spelling of lease math on a raw clock —
    dict entry, comparison, keyword argument, aliased datetime — but
    only inside seaweedfs_tpu/, never in clockctl.py (the home), and
    never when the expression reads clockctl or carries no clock call
    at all (comparing expires_at against a prefetched `now` is THE
    sanctioned idiom)."""
    dict_entry = ("import time\n\ndef g(vid):\n"
                  "    return {'vid': vid, 'expires_at': "
                  "time.time() + 30}\n")
    assert "lease-wall-clock" in rules_of(dict_entry)
    # bench drivers and tests stamp wall-clock expiries legitimately
    assert "lease-wall-clock" not in rules_of(
        dict_entry, path="tools/bench_thing.py")
    assert "lease-wall-clock" not in rules_of(
        dict_entry, path="seaweedfs_tpu/utils/clockctl.py")
    # comparison: lease operand vs a raw clock read
    assert "lease-wall-clock" in rules_of(
        "import time\n\ndef f(l):\n"
        "    return l['expires_at'] <= time.monotonic()\n")
    # keyword-argument spelling
    assert "lease-wall-clock" in rules_of(
        "import time\n\ndef f(mk):\n"
        "    return mk(expires_at=time.time() + 30)\n")
    # aliased datetime still resolves to the canonical wall clock
    assert "lease-wall-clock" in rules_of(
        "from datetime import datetime as dt\n\ndef f(lease):\n"
        "    lease['expires_at'] = dt.utcnow().timestamp() + 30\n")
    # the sanctioned idiom: clock read once through clockctl, lease
    # arithmetic against the local snapshot
    assert "lease-wall-clock" not in rules_of(
        "from seaweedfs_tpu.utils import clockctl\n\ndef f(l):\n"
        "    now = clockctl.now()\n"
        "    return l['expires_at'] <= now\n")
    # non-lease wall-clock math is raw-clock's beat, not this rule's
    assert "lease-wall-clock" not in rules_of(
        "import time\n\ndef f():\n    t0 = time.time()\n    return t0\n")


def test_syntax_error_reported_not_crashed():
    vs = check_source("seaweedfs_tpu/x.py", "def broken(:\n")
    assert [v.rule for v in vs] == ["syntax-error"]


# ------------------------------------------------------- the engine

def _write(root: Path, rel: str, src: str) -> Path:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return p


def test_baseline_roundtrip(tmp_path):
    """--update-baseline then a plain run exits 0; a NEW violation on
    top of the grandfathered ones exits 1 and names only itself."""
    from tools.weedlint.__main__ import main

    _write(tmp_path, "seaweedfs_tpu/old.py",
           "import time\nx = time.time()\n")
    args = ["--root", str(tmp_path)]
    assert main(args + ["--update-baseline"]) == 0
    assert main(args) == 0  # grandfathered

    _write(tmp_path, "seaweedfs_tpu/new.py",
           "import time\ny = time.monotonic()\n")
    assert main(args) == 1

    baseline = engine.load_baseline(tmp_path / engine.BASELINE_NAME)
    fresh = engine.filter_new(
        engine.lint_tree(tmp_path), baseline)
    assert [v.file for v in fresh] == ["seaweedfs_tpu/new.py"]


def test_baseline_keys_survive_line_drift(tmp_path):
    """Baseline entries match on (file, rule, snippet), not line
    numbers — inserting unrelated lines above must not re-flag."""
    p = _write(tmp_path, "seaweedfs_tpu/drift.py",
               "import time\nx = time.time()\n")
    base = Counter(v.key() for v in engine.lint_tree(tmp_path))
    p.write_text("import time\n\n# padding\nA = 1\nx = time.time()\n")
    assert engine.filter_new(engine.lint_tree(tmp_path), base) == []


def test_filter_new_is_a_consuming_multiset(tmp_path):
    """One grandfathered entry covers ONE occurrence: duplicating the
    identical violating line is a new violation."""
    p = _write(tmp_path, "seaweedfs_tpu/dup.py",
               "import time\nx = time.time()\n")
    base = Counter(v.key() for v in engine.lint_tree(tmp_path))
    p.write_text("import time\nx = time.time()\nx = time.time()\n")
    fresh = engine.filter_new(engine.lint_tree(tmp_path), base)
    assert len(fresh) == 1


def test_diff_mode_lints_only_changed_files(tmp_path):
    """Synthetic two-commit repo: commit 1 carries an old violation,
    commit 2 adds a second file; --diff REV sees only the new file
    (plus untracked)."""
    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True,
                       capture_output=True,
                       env={"GIT_AUTHOR_NAME": "t",
                            "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t",
                            "HOME": str(tmp_path),
                            "PATH": "/usr/bin:/bin:/usr/local/bin"})

    git("init", "-q")
    _write(tmp_path, "seaweedfs_tpu/legacy.py",
           "import time\nx = time.time()\n")
    git("add", "-A")
    git("commit", "-qm", "one")
    first = subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=tmp_path, check=True,
        capture_output=True, text=True).stdout.strip()
    _write(tmp_path, "seaweedfs_tpu/fresh.py",
           "import time\ny = time.monotonic()\n")
    git("add", "-A")
    git("commit", "-qm", "two")
    _write(tmp_path, "seaweedfs_tpu/untracked.py",
           "import time\ntime.sleep(0)\n")

    changed = engine.changed_files(tmp_path, first)
    rels = sorted(p.relative_to(tmp_path).as_posix() for p in changed)
    assert rels == ["seaweedfs_tpu/fresh.py",
                    "seaweedfs_tpu/untracked.py"]
    vs = engine.lint_tree(tmp_path, files=changed)
    assert sorted({v.file for v in vs}) == rels


def test_cli_runs_as_module(tmp_path):
    """`python -m tools.weedlint` is the documented entry point."""
    _write(tmp_path, "seaweedfs_tpu/v.py", "import time\nt = time.time()\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.weedlint", "--root", str(tmp_path),
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 1
    assert "seaweedfs_tpu/v.py:2:raw-clock" in out.stdout


# ------------------------------------------------------ the tree gate

def test_repo_tree_lints_clean_within_budget():
    """THE gate: the real tree has zero non-baselined violations, and
    the whole-tree walk fits the 5s budget the tier-1 suite pays."""
    t0 = time.perf_counter()
    violations = engine.lint_tree(REPO)
    elapsed = time.perf_counter() - t0
    baseline = engine.load_baseline(REPO / engine.BASELINE_NAME)
    fresh = engine.filter_new(violations, baseline)
    assert fresh == [], "new weedlint violations:\n" + "\n".join(
        v.format() for v in fresh)
    assert elapsed < 5.0, f"tree lint took {elapsed:.2f}s"


def test_baseline_burned_down_at_least_60_entries():
    """The PR's burn-down contract: the checked-in baseline is >=60
    entries smaller than the initial capture (frozen when the linter
    first ran over the tree)."""
    initial = json.loads(
        (REPO / "tools/weedlint/baseline_initial.json").read_text())
    current = json.loads(
        (REPO / engine.BASELINE_NAME).read_text())
    shrink = len(initial["entries"]) - len(current["entries"])
    assert shrink >= 60, \
        f"baseline shrank by only {shrink} entries"


def test_baseline_matches_tree_exactly():
    """No phantom grandfathering: every baseline entry corresponds to a
    live violation, so the ratchet can only tighten."""
    live = Counter(v.key() for v in engine.lint_tree(REPO))
    base = engine.load_baseline(REPO / engine.BASELINE_NAME)
    stale = base - live
    assert not stale, f"baseline entries with no live violation: " \
                      f"{sorted(stale)[:5]}"
