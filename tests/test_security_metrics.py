"""JWT auth, IP guard, metrics exposition, gzip storage."""

import gzip
import time

import pytest

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call
from seaweedfs_tpu.utils.metrics import Registry
from seaweedfs_tpu.utils.security import Guard, gen_jwt, verify_jwt


def test_jwt_roundtrip():
    tok = gen_jwt("secret", "3,abc123")
    assert verify_jwt("secret", tok, "3,abc123")
    assert not verify_jwt("wrong", tok, "3,abc123")
    assert not verify_jwt("secret", tok, "4,zzz")
    assert not verify_jwt("secret", tok + "x", "3,abc123")
    expired = gen_jwt("secret", "3,abc123", expires_seconds=-5)
    assert not verify_jwt("secret", expired, "3,abc123")


def test_guard():
    g = Guard(["10.0.0.0/8", "127.0.0.1"])
    assert g.allowed("10.1.2.3")
    assert g.allowed("127.0.0.1")
    assert not g.allowed("192.168.1.1")
    assert Guard([]).allowed("8.8.8.8")


def test_metrics_text_format():
    r = Registry()
    c = r.counter("master", "assign_total", "assigns")
    c.inc()
    c.inc()
    h = r.histogram("volumeServer", "request_seconds", "lat", ("type",))
    h.observe(0.005, "read")
    text = r.expose_text()
    assert "SeaweedFS_TPU_master_assign_total 2.0" in text
    assert 'type="read"' in text and "_bucket" in text
    assert "# TYPE SeaweedFS_TPU_master_assign_total counter" in text


@pytest.fixture
def secure_cluster(tmp_path):
    master = MasterServer(jwt_signing_key="topsecret")
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    time.sleep(0.1)
    yield master, vs
    vs.stop()
    master.stop()


def test_jwt_enforced_on_writes(secure_cluster):
    master, vs = secure_cluster
    mc = MasterClient(master.url)
    # via operation (auth token from assign): succeeds
    res = operation.upload_data(mc, b"secure payload")
    assert operation.read_data(mc, res.fid) == b"secure payload"

    # raw write without token: rejected
    a = mc.assign()
    status, body, _ = http_call(
        "POST", f"http://{a['url']}/{a['fid']}", body=b"x")
    assert status == 401

    # with token: accepted
    status, _, _ = http_call(
        "POST", f"http://{a['url']}/{a['fid']}", body=b"x",
        headers={"Authorization": f"Bearer {a['auth']}"})
    assert status == 201


def test_metrics_endpoints(secure_cluster):
    master, vs = secure_cluster
    mc = MasterClient(master.url)
    operation.upload_data(mc, b"data")
    status, body, _ = http_call("GET", f"http://{master.url}/metrics")
    assert status == 200 and b"assign_total" in body
    status, body, _ = http_call("GET", f"http://{vs.url}/metrics")
    assert status == 200 and b"request_total" in body


def test_gzip_storage_roundtrip(tmp_path):
    master = MasterServer()
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    time.sleep(0.1)
    try:
        mc = MasterClient(master.url)
        data = b"A" * 10000  # compressible
        res = operation.upload_data(mc, data, compress=True)
        # plain read: transparently decompressed
        assert operation.read_data(mc, res.fid) == data
        # gzip-accepting read: raw compressed bytes + header
        status, body, headers = http_call(
            "GET", f"http://{vs.url}/{res.fid}",
            headers={"Accept-Encoding": "gzip"})
        assert headers.get("Content-Encoding") == "gzip"
        assert gzip.decompress(body) == data
        assert len(body) < len(data)
    finally:
        vs.stop()
        master.stop()
