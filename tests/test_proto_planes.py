"""Round-4 proto surfaces (reference weed/pb/{remote,iam,s3,mount}.proto):
remote conf/mapping proto-bytes persistence with legacy-JSON fallback,
the S3 Configure RPC on the filer gRPC plane, circuit-breaker
hot-reload from /etc/s3/circuit_breaker, and the mount admin plane."""

import json
import time

import pytest

from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v")], master.url)
    vs.start()
    fs = FilerServer(master.url, grpc_port=0)
    fs.start()
    time.sleep(0.1)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def test_remote_conf_proto_persistence_and_json_fallback(stack):
    from seaweedfs_tpu.filer.remote_mount import (REMOTE_CONF_KV_KEY,
                                                  RemoteMounts)
    from seaweedfs_tpu.pb import remote_pb2
    from seaweedfs_tpu.remote_storage.remote_storage import RemoteConf
    master, vs, fs = stack
    rm = RemoteMounts(fs.filer)
    rm.configure(RemoteConf(name="cloud", type="s3",
                            endpoint="http://e", access_key="AK",
                            secret_key="SK", bucket="b"))
    # at rest: weedtpu_remote_pb bytes, not JSON
    blob = fs.filer.store.kv_get(REMOTE_CONF_KV_KEY)
    lst = remote_pb2.RemoteConfList.FromString(blob)
    assert lst.remotes[0].name == "cloud"
    assert lst.remotes[0].secret_key == "SK"
    assert rm.list_confs()["cloud"].endpoint == "http://e"

    # a pre-round-4 JSON blob still reads, and re-saving migrates it
    fs.filer.store.kv_put(REMOTE_CONF_KV_KEY, json.dumps(
        {"remotes": [{"name": "old", "type": "local",
                      "root": "/tmp/x"}]}).encode())
    assert rm.list_confs()["old"].root == "/tmp/x"
    rm.configure(RemoteConf(name="extra"))
    lst = remote_pb2.RemoteConfList.FromString(
        fs.filer.store.kv_get(REMOTE_CONF_KV_KEY))
    assert sorted(c.name for c in lst.remotes) == ["extra", "old"]

    # mappings: same scheme
    rm.mount("/m", "old")
    raw = fs.filer.store.kv_get(b"/etc/remote.mapping")
    m = remote_pb2.RemoteStorageMapping.FromString(raw)
    assert m.mappings["/m"].name == "old"
    assert rm.list_mappings()["/m"]["remote_name"] == "old"


def test_s3_configure_rpc(stack):
    from seaweedfs_tpu.gateway.iam_server import IdentityStore
    from seaweedfs_tpu.pb import iam_pb2, s3_pb2
    from seaweedfs_tpu.utils.tls import make_channel
    master, vs, fs = stack
    chan = make_channel(f"127.0.0.1:{fs.grpc_port}", role="client")
    fn = chan.unary_unary(
        "/weedtpu_s3_pb.SeaweedTpuS3/Configure",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=s3_pb2.S3ConfigureResponse.FromString)

    api = iam_pb2.S3ApiConfiguration(identities=[iam_pb2.Identity(
        name="alice",
        credentials=[iam_pb2.Credential(access_key="AKIA1",
                                        secret_key="s3cr3t")],
        actions=["Read", "Write"])])
    fn(s3_pb2.S3ConfigureRequest(
        s3_configuration_file_content=api.SerializeToString()), timeout=10)
    conf = IdentityStore(fs.filer).load()
    assert conf["identities"][0]["name"] == "alice"
    assert conf["identities"][0]["credentials"][0]["accessKey"] == "AKIA1"

    # legacy JSON payload is accepted too
    fn(s3_pb2.S3ConfigureRequest(s3_configuration_file_content=json.dumps(
        {"identities": [{"name": "bob", "credentials": [],
                         "actions": []}]}).encode()), timeout=10)
    assert IdentityStore(fs.filer).load()["identities"][0]["name"] == "bob"

    import grpc
    with pytest.raises(grpc.RpcError):
        fn(s3_pb2.S3ConfigureRequest(
            s3_configuration_file_content=b"\xff\xfegarbage that is "
            b"neither proto nor json"), timeout=10)
    # a JSON scalar must be INVALID_ARGUMENT, not an UNKNOWN crash
    with pytest.raises(grpc.RpcError) as exc:
        fn(s3_pb2.S3ConfigureRequest(
            s3_configuration_file_content=b"42"), timeout=10)
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    chan.close()


def test_circuit_breaker_hot_reload(stack):
    from seaweedfs_tpu.gateway.s3_server import S3Server
    from seaweedfs_tpu.shell.commands import ShellContext
    from seaweedfs_tpu.shell.repl import run_command
    master, vs, fs = stack
    s3 = S3Server(fs, access_key="k", secret_key="s")
    s3.start()
    try:
        sh = ShellContext(master.url)
        out = run_command(
            sh, "s3.circuitbreaker -read 7 -write 3")
        assert out["global"] == {"enabled": True,
                                 "actions": {"Read": 7, "Write": 3}}
        out = run_command(sh, "s3.circuitbreaker -bucket pics -read 1")
        assert out["buckets"]["pics"]["actions"] == {"Read": 1}

        s3._cb_state = (0.0, -1.0)  # expire the TTL
        s3._refresh_breaker()
        assert s3.breaker.global_limits == {"Read": 7, "Write": 3}
        assert s3.breaker.bucket_limits == {"pics": {"Read": 1}}
        # the per-bucket Read limit of 1 actually trips
        assert s3.breaker.acquire("pics", "Read")
        assert not s3.breaker.acquire("pics", "Read")
        s3.breaker.release("pics", "Read")

        out = run_command(sh, "s3.circuitbreaker -disable")
        assert out["global"]["enabled"] is False
        s3._cb_state = (0.0, -1.0)
        s3._refresh_breaker()
        assert s3.breaker.global_limits == {}

        # query of an unconfigured bucket must not vivify it
        out = run_command(sh, "s3.circuitbreaker -bucket ghost")
        assert "ghost" not in out["buckets"]
        out = run_command(sh, "s3.circuitbreaker")
        assert "ghost" not in out["buckets"]

        # a config too big to inline (filer chunks >2KB) still loads
        from seaweedfs_tpu.pb import s3_pb2
        big = s3_pb2.S3CircuitBreakerConfig()
        for i in range(200):
            big.buckets[f"bucket-{i:04d}"].enabled = True
            big.buckets[f"bucket-{i:04d}"].actions["Read"] = i + 1
        blob = big.SerializeToString()
        assert len(blob) > 2048
        from seaweedfs_tpu.utils.httpd import http_call
        status, _, _ = http_call(
            "POST", f"http://{fs.url}/etc/s3/circuit_breaker", body=blob)
        assert status < 300
        s3._cb_state = (0.0, -1.0)
        s3._refresh_breaker()
        assert s3.breaker.bucket_limits["bucket-0199"] == {"Read": 200}
    finally:
        s3.stop()


def test_mount_admin_plane(stack):
    from seaweedfs_tpu.mount.mount_grpc import (MountAdminClient,
                                                start_mount_grpc)
    from seaweedfs_tpu.mount.weedfs import WeedFS
    from seaweedfs_tpu.shell.commands import ShellContext
    from seaweedfs_tpu.shell.repl import run_command
    master, vs, fs = stack
    w = WeedFS(fs)
    server, port, stop = start_mount_grpc(w, master_url=master.url)
    try:
        base = w.statfs()
        assert base is not None  # cluster capacity visible
        client = MountAdminClient(f"127.0.0.1:{port}")
        quota = 1 << 30
        assert client.configure(quota) == quota
        blocks, bfree, *_ = w.statfs()
        assert blocks == quota // 4096
        assert client.configure(-1) == quota  # query leaves it alone

        # the shell finds the mount through the master's registry
        deadline = time.time() + 5
        while time.time() < deadline:
            sh = ShellContext(master.url)
            out = run_command(
                sh, "mount.configure -collectionCapacity 2147483648")
            if out["mounts"]:
                break
            time.sleep(0.2)
        assert out["mounts"] == {f"127.0.0.1:{port}": 2 << 30}
        w._statfs_cache = None
        assert w.statfs()[0] == (2 << 30) // 4096
        client.close()
    finally:
        stop.set()
        server.stop(grace=None)


def test_mq_proto_file_count():
    """All eight reference proto surfaces have a weedtpu counterpart
    (reference weed/pb: master, volume_server, filer, remote, iam, s3,
    mount, mq)."""
    import pathlib

    import seaweedfs_tpu.pb as pb_pkg
    pb_dir = pathlib.Path(pb_pkg.__file__).parent
    protos = {p.stem for p in pb_dir.glob("*.proto")}
    assert {"master", "volume_server", "filer", "remote", "iam", "s3",
            "mount", "mq"} <= protos
    for name in protos:
        assert (pb_dir / f"{name}_pb2.py").exists(), f"{name} not compiled"