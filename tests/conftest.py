"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (bench.py, by contrast, runs on the
real chip and must NOT import this)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
