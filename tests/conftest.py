"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (bench.py, by contrast, runs on the
real chip and must NOT import this).

Note: this environment's sitecustomize registers the TPU backend and forces
jax_platforms — the config update below (after env vars, before any backend
use) overrides it back to CPU.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
