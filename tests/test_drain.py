"""Graceful drain end to end: HTTP layer, volume-server stop(), and
the master-side exclusions (assign, growth, repair drain grace).

The rolling-restart acceptance bar: draining a volume server under
live write traffic must be invisible — zero failed client requests
and zero repair-queue entries for the drained node's volumes."""

import threading
import time

import pytest

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import HttpServer, http_call


# ------------------------------------------------------------ HTTP layer

def test_http_drain_waits_for_inflight():
    srv = HttpServer()
    release = threading.Event()

    @srv.route("GET", "/slow")
    def slow(req):
        release.wait(5.0)
        from seaweedfs_tpu.utils.httpd import Response
        return Response(b"done", content_type="text/plain")

    srv.start()
    url = f"http://{srv.host}:{srv.port}/slow"
    got = {}

    def client():
        got["status"], got["body"], _ = http_call("GET", url)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    deadline = time.time() + 5
    while srv._inflight == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert srv._inflight == 1

    done = {}

    def drainer():
        done["idle"] = srv.drain(timeout=5.0)

    d = threading.Thread(target=drainer, daemon=True)
    d.start()
    time.sleep(0.1)
    assert srv.draining and not done  # still waiting on the slow request
    release.set()
    d.join(timeout=5)
    t.join(timeout=5)
    assert done["idle"] is True       # went idle within the timeout
    assert got["status"] == 200 and got["body"] == b"done"
    srv.stop()


def test_http_draining_rejects_new_requests():
    srv = HttpServer()

    @srv.route("GET", "/ping")
    def ping(req):
        from seaweedfs_tpu.utils.httpd import Response
        return Response({"ok": True})

    srv.start()
    url = f"http://{srv.host}:{srv.port}/ping"
    status, _, _ = http_call("GET", url)
    assert status == 200
    # flip the flag without shutting the listener down: requests still
    # reach dispatch, which must shed them with a retry hint
    srv.draining = True
    status, body, headers = http_call("GET", url)
    assert status == 503
    assert {k.lower(): v for k, v in headers.items()}["retry-after"] == "1"
    assert b"draining" in body
    srv.draining = False
    status, _, _ = http_call("GET", url)
    assert status == 200
    srv.stop()


def test_http_drain_idempotent_and_safe_before_start():
    srv = HttpServer()
    assert srv.drain(timeout=0.1) is True   # never started: trivially idle
    assert srv.drain(timeout=0.1) is True
    srv.stop()


# -------------------------------------------- rolling drain, real servers

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(3):
        vs = VolumeServer([str(tmp_path / f"v{i}")], master.url,
                          rack=f"r{i % 2}", data_center="dc1")
        vs.start()
        servers.append(vs)
    deadline = time.time() + 5
    while (len(master.topo.all_nodes()) < 3
           and time.time() < deadline):
        time.sleep(0.05)
    yield master, servers
    for vs in servers:
        vs.stop(graceful=False)
    master.stop()


def test_drain_invisible_under_live_writes(cluster):
    master, servers = cluster
    mc = MasterClient(master.url)
    payload = b"drain-smoke-payload" * 16
    failures: list[str] = []
    ops = [0]
    done = threading.Event()

    def one_write() -> bool:
        # a fresh assign per attempt, like a filer: after a connection
        # error the retry routes through the master again, which by
        # then has excluded the draining node
        for _ in range(2):
            try:
                a = mc.assign()
                operation.upload_to(a["fid"], a["url"], payload)
                return True
            except Exception:
                continue
        return False

    def writer():
        while not done.is_set():
            if not one_write():
                failures.append("write failed after retry")
            ops[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # steady-state traffic, volumes grown

    # drain the first server that actually holds volumes
    victim = next((vs for vs in servers
                   if any(loc.volumes for loc in vs.store.locations)),
                  servers[0])
    vids = sorted(vid for loc in victim.store.locations
                  for vid in loc.volumes)
    victim.stop()  # graceful by default
    time.sleep(0.4)  # traffic keeps flowing against the survivors
    done.set()
    for t in threads:
        t.join(timeout=5)

    assert not failures, failures[:5]
    assert ops[0] > 50  # the invariant means something: real traffic ran

    node = next(n for n in master.topo.all_nodes()
                if n.public_url == victim.url)
    assert node.draining
    st = master.repair_queue.status()
    if vids:  # the victim's volumes sit under drain grace, not repair
        assert set(vids) <= set(st["drain_grace_vids"])
    assert not [t for t in st["queue"] + st["in_flight"]
                if t.get("volume_id") in set(vids)]

    # the cluster still takes writes after the drain completed
    a = mc.assign()
    res = operation.upload_to(a["fid"], a["url"], payload)
    assert res is not None
