"""S3-protocol remote storage client (round-2/3 verdict gap #2):
an S3Remote speaks SigV4 to any S3-compatible endpoint — here the
repo's OWN gateway, standing in for a cloud bucket. Covers the SPI,
remote mount + metadata pull + cache/uncache/writeback through the
filer, exactly like the local backend tests but across the wire.
Reference: weed/remote_storage/s3/s3_storage_client.go."""

import time

import pytest

from seaweedfs_tpu.gateway.s3_server import S3Server
from seaweedfs_tpu.remote_storage.remote_storage import (RemoteConf,
                                                         make_remote_client)
from seaweedfs_tpu.remote_storage.s3_client import S3Remote
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.httpd import http_call


@pytest.fixture
def cloud(tmp_path):
    """A full 'cloud': master + volume + filer + SigV4-authenticated S3
    gateway, plus a LOCAL cluster (second filer) that mounts it."""
    master = MasterServer(volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url)
    vs.start()
    cloud_fs = FilerServer(master.url)
    cloud_fs.start()
    s3 = S3Server(cloud_fs, access_key="AKIDEXAMPLE",
                  secret_key="wJalrXUtnFEMI")
    s3.start()
    local_fs = FilerServer(master.url)
    local_fs.start()
    time.sleep(0.2)
    yield s3, local_fs
    local_fs.stop()
    s3.stop()
    cloud_fs.stop()
    vs.stop()
    master.stop()


def _mk_bucket(s3, name: str):
    from seaweedfs_tpu.remote_storage.s3_client import SigV4Signer
    signer = SigV4Signer("AKIDEXAMPLE", "wJalrXUtnFEMI")
    headers = signer.signed_headers(
        "PUT", f"127.0.0.1:{s3.http.port}", f"/{name}", {}, b"")
    status, body, _ = http_call(
        "PUT", f"http://127.0.0.1:{s3.http.port}/{name}", headers=headers)
    assert status < 300, body


def test_s3_remote_client_spi(cloud):
    s3, _ = cloud
    _mk_bucket(s3, "cloudbucket")
    c = make_remote_client(RemoteConf(
        name="aws", type="s3", endpoint=f"127.0.0.1:{s3.http.port}",
        bucket="cloudbucket", access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI"))
    assert isinstance(c, S3Remote)

    c.write_file("docs/a.txt", b"alpha")
    c.write_file("docs/deep/b.bin", b"B" * 5000)
    c.write_file("top.txt", b"top")

    assert c.read_file("docs/a.txt") == b"alpha"
    assert c.read_file("docs/deep/b.bin", offset=10, size=20) == b"B" * 20

    st = c.stat("docs/a.txt")
    assert st is not None and st.size == 5 and st.etag
    assert c.stat("missing.txt") is None

    listing = list(c.traverse())
    files = {f.path: f for f in listing if not f.is_directory}
    dirs = {f.path for f in listing if f.is_directory}
    assert set(files) == {"docs/a.txt", "docs/deep/b.bin", "top.txt"}
    assert {"docs", "docs/deep"} <= dirs
    assert files["docs/deep/b.bin"].size == 5000
    assert files["docs/a.txt"].etag == st.etag

    # prefix traverse
    sub = {f.path for f in c.traverse("docs/deep") if not f.is_directory}
    assert sub == {"docs/deep/b.bin"}

    c.remove_file("top.txt")
    assert c.stat("top.txt") is None


def test_gcs_b2_types_ride_the_s3_dialect(cloud):
    """gcs/b2/wasabi are S3-dialect endpoints: the same client serves
    them, pointed at the provider's interop endpoint (here the local
    gateway stands in)."""
    s3, _ = cloud
    _mk_bucket(s3, "interop")
    for t in ("gcs", "b2", "wasabi"):
        c = make_remote_client(RemoteConf(
            name=t, type=t, endpoint=f"127.0.0.1:{s3.http.port}",
            bucket="interop", access_key="AKIDEXAMPLE",
            secret_key="wJalrXUtnFEMI"))
        assert isinstance(c, S3Remote)
        c.write_file(f"{t}.txt", t.encode())
        assert c.read_file(f"{t}.txt") == t.encode()
    # azure speaks its own wire protocol via the SharedKey REST client
    # (tests/test_azure_remote.py); a truly unknown type stays a plug
    # point
    with pytest.raises(NotImplementedError):
        make_remote_client(RemoteConf(name="x", type="hdfs"))


def test_s3_remote_bad_credentials_rejected(cloud):
    s3, _ = cloud
    _mk_bucket(s3, "lockedbucket")
    bad = S3Remote(f"127.0.0.1:{s3.http.port}", "lockedbucket",
                   access_key="AKIDEXAMPLE", secret_key="WRONG")
    with pytest.raises(IOError):
        bad.write_file("x.txt", b"nope")


def test_s3_remote_mount_pull_cache_writeback(cloud, tmp_path):
    """The full remote-mount lifecycle against the S3 remote: configure
    + mount + meta pull + read-through + cache + writeback (reference
    shell remote.mount/remote.cache + filer.remote.sync)."""
    s3, local_fs = cloud
    _mk_bucket(s3, "mnt")
    conf = RemoteConf(name="cloudy", type="s3",
                      endpoint=f"127.0.0.1:{s3.http.port}", bucket="mnt",
                      access_key="AKIDEXAMPLE",
                      secret_key="wJalrXUtnFEMI")
    # seed the "cloud"
    seed = make_remote_client(conf)
    seed.write_file("photos/cat.jpg", b"\xff\xd8meow" * 100)
    seed.write_file("notes.md", b"# hello from the cloud")

    rm = local_fs.remote_mounts
    rm.configure(conf)
    rm.mount("/clouddata", "cloudy")
    n = rm.pull_metadata("/clouddata")
    assert n >= 2

    # metadata only: entries carry RemoteEntry, no chunks yet
    e = local_fs.filer.find_entry("/clouddata/notes.md")
    assert e is not None and e.remote is not None and not e.chunks
    assert e.file_size() == len(b"# hello from the cloud")

    # read-through via the filer HTTP plane fetches from the S3 remote
    status, body, _ = http_call(
        "GET", f"http://{local_fs.url}/clouddata/notes.md")
    assert status == 200 and body == b"# hello from the cloud"

    # cache materializes local chunks
    status, body, _ = http_call(
        "POST", f"http://{local_fs.url}/__api/remote/cache",
        json_body={"path": "/clouddata/photos/cat.jpg"})
    assert status == 200, body
    e = local_fs.filer.find_entry("/clouddata/photos/cat.jpg")
    assert e.chunks
    status, body, _ = http_call(
        "GET", f"http://{local_fs.url}/clouddata/photos/cat.jpg")
    assert status == 200 and body == b"\xff\xd8meow" * 100

    # uncache drops the local copy, keeps the remote pointer
    status, _, _ = http_call(
        "POST", f"http://{local_fs.url}/__api/remote/uncache",
        json_body={"path": "/clouddata/photos/cat.jpg"})
    assert status == 200
    e = local_fs.filer.find_entry("/clouddata/photos/cat.jpg")
    assert not e.chunks and e.remote is not None

    # local write + writeback pushes to the cloud
    status, _, _ = http_call(
        "POST", f"http://{local_fs.url}/clouddata/new.txt",
        body=b"written locally")
    assert status < 300
    status, body, _ = http_call(
        "POST", f"http://{local_fs.url}/__api/remote/writeback",
        json_body={"path": "/clouddata/new.txt"})
    assert status == 200, body
    assert seed.read_file("new.txt") == b"written locally"
